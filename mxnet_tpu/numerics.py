"""Numerical-health guard: fused finite-checks, skip-step, clipping,
divergence auto-recovery.

A single NaN/Inf gradient silently corrupts optimizer state and poisons
every later step — the failure mode large bf16/f16 runs hit most often.
The reference stack guards against this with `DynamicLossScaler.has_overflow`,
which does one blocking `asnumpy()` readback PER GRADIENT and therefore
defeats the fused-step pipelining (PR 2/3).  Here the guard lives inside
the compiled programs instead:

- `grad_health(raws)` — ONE cached jit over the step's raw gradient
  arrays returning a tiny ``(2,)`` f32 device array
  ``[all_finite, global_sq_norm]``.  No host sync happens at this point;
  the array stays on device.
- `StepGuard` — carries that device array into the fused optimizer
  programs (`optimizer/grouped.py`), which compute the health predicate
  IN-TRACE and `jnp.where` the updated weights/states against the
  originals.  An unhealthy step therefore leaves weights and optimizer
  state bitwise-unchanged without any extra dispatch, and a healthy step
  is bitwise-identical to the unguarded program (`where` with a true
  predicate is the identity; donation semantics are preserved).
- Exactly ONE scalar readback per step: the Trainer materializes the
  health array once, AFTER the update dispatch, so XLA pipelines the
  guard with the step.  `readback_count()` regression-tests this.
- `DivergenceMonitor` — host-side EWMA tracking of loss/grad-norm that,
  after `MXTPU_MAX_BAD_STEPS` consecutive unhealthy or exploding steps,
  rolls back to the last `resilience.LocalCheckpointer` snapshot with a
  re-seeded loss scale and quarantines the offending batch indices.

Env knobs (docs/env_vars.md): ``MXTPU_GRAD_GUARD`` (default 1),
``MXTPU_MAX_BAD_STEPS`` (default 25), ``MXTPU_CLIP_GLOBAL_NORM``
(unset = no clipping).  Fault-injection sites (docs/resilience.md):
``nan_grad`` poisons one gradient before health assessment;
``inf_loss`` corrupts the loss seen by `DivergenceMonitor.observe`.
"""

from __future__ import annotations

import logging
import math
import os

from .base import MXNetError

_LOG = logging.getLogger("mxnet_tpu.numerics")


# -- env plumbing --------------------------------------------------------------

def grad_guard_enabled() -> bool:
    """MXTPU_GRAD_GUARD gate (default on); 0/false/off disables the
    fused finite-check + skip-step machinery.  Read at each step."""
    return os.environ.get("MXTPU_GRAD_GUARD", "1").lower() \
        not in ("0", "false", "off", "")


def max_bad_steps(default=25) -> int:
    """MXTPU_MAX_BAD_STEPS: consecutive unhealthy/exploding steps before
    `DivergenceMonitor` declares divergence and rolls back."""
    try:
        return int(os.environ.get("MXTPU_MAX_BAD_STEPS", default))
    except ValueError:
        return default


def clip_global_norm_env():
    """MXTPU_CLIP_GLOBAL_NORM as a float, or None when unset/<=0."""
    raw = os.environ.get("MXTPU_CLIP_GLOBAL_NORM")
    if not raw:
        return None
    try:
        val = float(raw)
    except ValueError:
        return None
    return val if val > 0.0 else None


# -- readback accounting (regression-tested: one host sync per step) -----------

_READBACK_COUNT = 0


def readback_count() -> int:
    """Number of health-scalar host readbacks since the last reset —
    exactly one per guarded step (the `StepGuard` materialization)."""
    return _READBACK_COUNT


def reset_readback_count() -> None:
    global _READBACK_COUNT
    _READBACK_COUNT = 0


# -- the fused health reduction ------------------------------------------------

_HEALTH_FN = None
_COMBINE_FN = None


def health_of(arrs):
    """Pure, traceable health reduction over raw arrays → ``(2,)`` f32
    ``[all_finite, global_sq_norm]``.  The ONE home of the health math:
    `grad_health` jits it for the eager path, and the whole-step capture
    (`gluon/captured.py`) inlines it so both paths reduce in the same
    order with the same accumulator dtype."""
    import jax.numpy as jnp

    # f32 accumulation: f16/bf16 inf/nan survive the upcast, and
    # the squared norm of a large group would overflow in f16.
    fin = jnp.bool_(True)
    sq = jnp.zeros((), jnp.float32)
    for a in arrs:
        af = a.astype(jnp.float32)
        fin = fin & jnp.all(jnp.isfinite(af))
        sq = sq + jnp.sum(jnp.square(af))
    return jnp.stack([fin.astype(jnp.float32), sq])


def _health_fn():
    global _HEALTH_FN
    if _HEALTH_FN is None:
        import jax

        _HEALTH_FN = jax.jit(health_of)
    return _HEALTH_FN


def grad_health(raws):
    """ONE jit dispatch over the step's raw gradient arrays → a ``(2,)``
    f32 device array ``[all_finite, global_sq_norm]``.  Nothing is read
    back to the host here; jit caches per (shapes, dtypes) structure.

    Sharding-aware by construction: jit keys on the inputs' committed
    shardings, so mesh-sharded gradients (parallel/sharding.py
    shard_model) get their own specialization in which GSPMD reduces
    each shard locally and psums the ``(2,)`` partials — the guard
    never gathers a full gradient."""
    return _health_fn()(list(raws))


def combine_health(parts):
    """Fold per-bucket ``(2,)`` health partials (e.g. one per allreduce
    bucket in `KVStore.bucketed_pushpull`) into one on device."""
    parts = list(parts)
    if len(parts) == 1:
        return parts[0]
    global _COMBINE_FN
    if _COMBINE_FN is None:
        import jax
        import jax.numpy as jnp

        def combine(cols):
            stacked = jnp.stack(cols)
            return jnp.stack([jnp.min(stacked[:, 0]),
                              jnp.sum(stacked[:, 1])])

        _COMBINE_FN = jax.jit(combine)
    return _COMBINE_FN(parts)


class StepGuard:
    """Per-step carrier for the device-resident health array.

    ``skip`` enables skip-step semantics (jnp.where in the fused
    programs + host-side skip of legacy-fallback items); ``clip`` bakes
    a global-norm clipping coefficient into the group programs.  The
    host readback happens at most ONCE, lazily, and is counted by
    `readback_count()`.
    """

    def __init__(self, health, skip=True, clip=None, extra=None):
        self.health = health          # (2,) f32 device array
        self.skip = bool(skip)
        self.clip = None if clip is None else float(clip)
        self.extra = extra            # (2,) u32 fingerprint (integrity)
        self._host = None
        self._extra_host = None

    def _materialize(self):
        if self._host is None:
            global _READBACK_COUNT
            _READBACK_COUNT += 1
            import numpy as _np

            from . import profiler

            # the step's ONE host sync: in a pipelined loop this span is
            # where the host waits out the device (bench.py reads it for
            # the readback share of the step-time breakdown).  The
            # integrity fingerprint (when the step computed one) rides
            # the same transfer — attestation adds no extra sync.
            with profiler.annotate("guard_readback"):
                if self.extra is not None:
                    import jax

                    v, e = jax.device_get((self.health, self.extra))
                    self._extra_host = _np.asarray(e)
                else:
                    v = _np.asarray(self.health)
            self._host = (float(v[0]), float(v[1]))
        return self._host

    @property
    def fingerprint(self):
        """The step's integrity fingerprint as one u64 int, or None
        when the program computed none (integrity off / not an
        attestation step).  Shares the single guard readback."""
        if self.extra is None:
            return None
        self._materialize()
        e = self._extra_host
        return (int(e[1]) << 32) | int(e[0])

    def peek(self):
        """``(all_finite, global_sq_norm)`` if the host readback already
        happened, else None.  Telemetry reads the guard through this so
        attaching grad-norm to a StepStats record never forces a sync
        the step would not have done anyway."""
        return self._host

    @property
    def healthy(self) -> bool:
        """True iff every gradient is finite AND the global squared norm
        itself is finite (an astronomically exploding-but-finite f32
        group can overflow the f32 accumulator — treated as unhealthy,
        matching the in-trace predicate)."""
        fin, sq = self._materialize()
        return fin > 0.0 and math.isfinite(sq)

    @property
    def grad_norm(self) -> float:
        """Global L2 norm of the step's gradients (host value)."""
        _, sq = self._materialize()
        return math.sqrt(sq) if sq >= 0.0 else float("nan")


class StepSkipped:
    """Record of one skipped optimizer step (Trainer.skipped_steps)."""

    __slots__ = ("step", "reason", "grad_norm", "loss_scale")

    def __init__(self, step, reason, grad_norm=None, loss_scale=None):
        self.step = step
        self.reason = reason
        self.grad_norm = grad_norm
        self.loss_scale = loss_scale

    def __repr__(self):
        extra = ""
        if self.grad_norm is not None:
            extra += f", grad_norm={self.grad_norm:g}"
        if self.loss_scale is not None:
            extra += f", loss_scale={self.loss_scale:g}"
        return f"StepSkipped(step={self.step}, reason={self.reason!r}{extra})"


# -- fault-injection hooks (docs/resilience.md) --------------------------------

def maybe_inject_nan_grad(grads) -> bool:
    """`nan_grad` fault site: poison element 0 of the first float
    gradient with NaN (in its backing array, so the health reduction,
    the allreduce and the update kernels all see the same poisoned
    value).  Consumes one armed count per call; returns True if it fired."""
    from . import resilience

    if not grads or not resilience.consume_fault("nan_grad"):
        return False
    import jax.numpy as jnp

    for g in grads:
        raw = getattr(g, "_data", None)
        if raw is None or not jnp.issubdtype(raw.dtype, jnp.floating):
            continue
        poisoned = raw.ravel().at[0].set(jnp.nan).reshape(raw.shape)
        g._set_data(poisoned)
        _LOG.warning("fault injection: poisoned gradient with NaN "
                     "(MXTPU_FAULT_INJECT nan_grad)")
        return True
    return False


# -- divergence monitoring -----------------------------------------------------

class DivergenceError(MXNetError):
    """Training diverged and no checkpointer was attached for rollback.

    Carries the failing window so the caller can triage (same spirit as
    `gluon.data.DataLoaderWorkerError` surfacing the failing batch):
    ``bad_steps`` (length of the unhealthy streak), ``step`` (last
    observed step), ``batch_indices`` (quarantined sample/batch indices
    seen during the streak, if the caller supplied them).
    """

    def __init__(self, msg, step=None, bad_steps=None, batch_indices=None):
        super().__init__(msg)
        self.step = step
        self.bad_steps = bad_steps
        self.batch_indices = list(batch_indices or [])


class DivergenceMonitor:
    """EWMA-based divergence detector with checkpoint auto-rollback.

    Feed it one `observe()` per step — either attach it to a Trainer
    (``trainer.divergence_monitor = mon``; the Trainer then calls
    ``observe(healthy=..., grad_norm=...)`` from the guarded step) or
    drive it manually with the loss.  Do NOT do both, or each training
    step counts as two observations.

    A step is **bad** when it is unhealthy (non-finite grads/loss) or
    when grad-norm/loss explodes past ``explode_factor`` × its EWMA.
    After ``max_bad_steps`` consecutive bad steps (MXTPU_MAX_BAD_STEPS):

    - with a ``checkpointer`` + ``set_state``: roll back to the newest
      valid `resilience.LocalCheckpointer` snapshot, re-seed the loss
      scale (``reseed_scale`` or current/scale_factor), quarantine the
      batch indices observed during the streak, and return True;
    - without one: raise `DivergenceError` carrying the streak context.
    """

    def __init__(self, checkpointer=None, set_state=None, scaler=None,
                 max_bad_steps=None, ewma_alpha=0.05, explode_factor=8.0,
                 reseed_scale=None, logger=None):
        self.checkpointer = checkpointer
        self.set_state = set_state
        self.scaler = scaler
        self.max_bad_steps = int(max_bad_steps) if max_bad_steps \
            else globals()["max_bad_steps"]()
        self.ewma_alpha = float(ewma_alpha)
        self.explode_factor = float(explode_factor)
        self.reseed_scale = reseed_scale
        self.logger = logger or _LOG
        self.loss_ewma = None
        self.norm_ewma = None
        self.bad_streak = 0
        self.recoveries = 0
        self.quarantined = []
        self._streak_batches = []
        self._last_step = None
        # optional resumable input pipeline (an object with
        # load_state_dict/quarantine, e.g. gluon.data.DataLoader built
        # with seed=): rollback rewinds it to the restored checkpoint's
        # sample offset and quarantines the streak's batches so replay
        # skips them (one `batch_quarantined` event per skip)
        self.data_pipeline = None

    def _is_bad(self, loss, grad_norm, healthy):
        if not healthy:
            return True
        if loss is not None and not math.isfinite(loss):
            return True
        if grad_norm is not None and not math.isfinite(grad_norm):
            return True
        if grad_norm is not None and self.norm_ewma is not None \
                and self.norm_ewma > 0.0 \
                and grad_norm > self.explode_factor * self.norm_ewma:
            return True
        if loss is not None and self.loss_ewma is not None \
                and abs(loss) > self.explode_factor \
                * max(abs(self.loss_ewma), 1e-8):
            return True
        return False

    def observe(self, step=None, loss=None, grad_norm=None, healthy=True,
                batch_indices=None) -> bool:
        """Record one training step; returns True iff a rollback ran."""
        from . import resilience

        if resilience.consume_fault("inf_loss"):
            loss = float("inf")
        self._last_step = step if step is not None else \
            (self._last_step + 1 if self._last_step is not None else 0)
        if self._is_bad(loss, grad_norm, healthy):
            self.bad_streak += 1
            if batch_indices is not None:
                self._streak_batches.extend(
                    batch_indices if isinstance(batch_indices, (list, tuple))
                    else [batch_indices])
            if self.bad_streak >= self.max_bad_steps:
                return self._recover()
            return False
        self.bad_streak = 0
        self._streak_batches = []
        a = self.ewma_alpha
        if loss is not None:
            self.loss_ewma = loss if self.loss_ewma is None \
                else (1.0 - a) * self.loss_ewma + a * loss
        if grad_norm is not None:
            self.norm_ewma = grad_norm if self.norm_ewma is None \
                else (1.0 - a) * self.norm_ewma + a * grad_norm
        return False

    def _recover(self) -> bool:
        from . import resilience

        bad, step = self.bad_streak, self._last_step
        self.quarantined.extend(self._streak_batches)
        batches = list(self._streak_batches)
        self._streak_batches = []
        self.bad_streak = 0
        restored = 0
        if self.checkpointer is not None and self.set_state is not None:
            restored = resilience.resume_latest(
                self.checkpointer, self.set_state, logger=self.logger)
        if self.checkpointer is None or self.set_state is None \
                or (restored == 0
                    and not getattr(self.checkpointer, "all_steps",
                                    lambda: [])()):
            raise DivergenceError(
                f"training diverged: {bad} consecutive unhealthy/exploding "
                f"steps (last step {step}; loss ewma "
                f"{self.loss_ewma}, grad-norm ewma {self.norm_ewma}); "
                f"quarantined batch indices: {batches or 'none supplied'}. "
                "Attach a resilience.LocalCheckpointer for auto-rollback, "
                "or lower the learning rate / re-seed the loss scale.",
                step=step, bad_steps=bad, batch_indices=batches)
        if self.scaler is not None:
            if self.reseed_scale is not None:
                self.scaler.loss_scale = float(self.reseed_scale)
            else:
                self.scaler.loss_scale = max(
                    1.0, self.scaler.loss_scale / self.scaler.scale_factor)
            self.scaler._unskipped = 0
        if self.data_pipeline is not None:
            ds_fn = getattr(self.checkpointer, "data_state", None)
            ds = ds_fn(restored) if ds_fn is not None else None
            if ds is not None:
                # rewind the pipeline to the checkpoint's exact sample
                # offset FIRST (load replaces the quarantine set), then
                # quarantine the streak so replay skips the poison
                self.data_pipeline.load_state_dict(ds)
            bad_ids = [tuple(b) for b in batches
                       if isinstance(b, (list, tuple)) and len(b) == 2]
            if bad_ids:
                self.data_pipeline.quarantine(bad_ids)
        self.recoveries += 1
        self.logger.warning(
            "divergence auto-recovery #%d: rolled back to checkpoint step "
            "%d after %d bad steps; quarantined batches: %s",
            self.recoveries, restored, bad, batches or "none supplied")
        from . import telemetry
        telemetry.event("divergence_rollback", step=restored,
                        bad_steps=bad, last_step=step,
                        quarantined=len(batches))
        return True
