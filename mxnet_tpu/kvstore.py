"""KVStore: parameter/gradient synchronization.

Reference parity: src/kvstore/ + python/mxnet/kvstore.py — KVStore.create
('local', 'device', 'nccl', 'dist_sync', 'dist_device_sync', 'dist_async'),
init/push/pull/pushpull, set_optimizer (server-side updates), optimizer-state
save/load, rank/num_workers.

TPU-first redesign (SURVEY.md §2.6): there is no parameter server — push+pull
is all-reduce.  Within a process, "devices" are a mesh sharding, and reduce
happens inside the jitted step (mxnet_tpu.parallel); the eager KVStore here
reduces the per-call value list (the reference's intra-node Comm tree) and,
for dist_* types on multi-process runs, all-reduces across hosts over
ICI/DCN using JAX global collectives.  ``dist_async``'s server-side-optimizer
semantics have no TPU analog and run synchronously (documented drop).
"""

from __future__ import annotations

import os
import pickle

from .base import MXNetError
from .ndarray.ndarray import NDArray, _from_jax
from . import optimizer as opt
from . import profiler
from . import resilience
from . import telemetry


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


def _bucket_bytes():
    """Flat-bucket byte budget for `bucketed_pushpull`
    (MXTPU_ALLREDUCE_BUCKET_MB, default 4 MB)."""
    try:
        mb = float(os.environ.get("MXTPU_ALLREDUCE_BUCKET_MB", "4"))
    except ValueError:
        mb = 4.0
    return max(1, int(mb * 1024 * 1024))


_ALLREDUCE_CACHE = {}

#: elastic membership epoch (bumped by `notify_mesh_reshape`): part of
#: every compiled-program fingerprint, because an N→M gang reshape can
#: leave jax's visible device set unchanged on a survivor while the
#: cross-process collective topology it compiled against is gone.
_MESH_EPOCH = 0


def notify_mesh_reshape(epoch):
    """Called by `resilience.ElasticGang.recover` after a membership
    change: invalidates every cached all-reduce program (and, through
    `device_fingerprint`, every captured whole-step program) so the
    first post-reshape step retraces against the new topology."""
    global _MESH_EPOCH
    _MESH_EPOCH = int(epoch)
    _ALLREDUCE_CACHE.clear()


def _device_fingerprint():
    """Cache key component: the current global device set plus the
    elastic membership epoch.  Invalidates compiled all-reduce programs
    if either changes across a preemption/restart or a gang reshape
    (the §5.3 recovery story)."""
    import jax

    return (_MESH_EPOCH,) + tuple(
        sorted((d.process_index, d.id) for d in jax.devices()))


def device_fingerprint():
    """Public alias of `_device_fingerprint` — part of every whole-step
    capture key (`gluon.captured`): a captured train-step program bakes
    in the device topology the same way the compiled all-reduce
    programs here do, and must retrace when it changes."""
    return _device_fingerprint()


def captured_step_compatible(kv):
    """Whether `gluon.captured` may subsume this trainer's gradient
    reduction into the whole-step program.  Today only the local fused
    path (no store: single worker, in-process arrays) qualifies; dist
    stores reduce through `bucketed_pushpull`, whose collectives run in
    their own compiled programs between backward and update, so the
    captured path defers to the eager oracle.  When the dist reduce
    moves in-program (a shard_map over `_per_process_mesh` around the
    gradient stack), this predicate is where it gets unlocked."""
    return kv is None


def _per_process_mesh():
    """One device per process: the DCN axis both eager collectives run
    over."""
    import numpy as _np

    import jax
    from jax.sharding import Mesh

    per_proc = {}
    for d in jax.devices():
        per_proc.setdefault(d.process_index, d)
    devs = [per_proc[p] for p in sorted(per_proc)]
    return Mesh(_np.asarray(devs), ("w",))


def _cross_process_allreduce(raw, label=None):
    """Eager cross-process all-reduce: each process contributes its local
    value; the summed result comes back replicated.  ``label`` names the
    guarding watchdog (bucketed callers pass dtype + byte size, so a
    `WatchdogExpired` says WHICH collective wedged).

    TPU-native path (SURVEY.md §2.6): per-process contributions become
    shards of a global array on a 1-device-per-process mesh, one jitted
    ``sum`` over the sharded axis lets GSPMD emit the all-reduce over
    ICI/DCN — no host gather, O(1) bandwidth vs the worker count
    (replaces the reference's ps-lite push/pull server hop).
    """
    import numpy as _np

    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    key = (tuple(raw.shape), str(raw.dtype), _device_fingerprint())
    entry = _ALLREDUCE_CACHE.get(key)
    if entry is None:
        mesh = _per_process_mesh()
        in_s = NamedSharding(mesh, PartitionSpec("w"))
        out_s = NamedSharding(mesh, PartitionSpec())
        fn = jax.jit(lambda x: x.sum(axis=0), in_shardings=in_s,
                     out_shardings=out_s)
        entry = (mesh, in_s, out_s, fn)
        _ALLREDUCE_CACHE[key] = entry
    mesh, in_s, out_s, fn = entry
    # watchdog around the blocking exchange: a dead peer stalls the
    # all-reduce forever; MXTPU_COLLECTIVE_TIMEOUT turns that into a
    # stack dump + clean error/abort (resilience.py)
    with resilience.guard_collective(label or "kvstore_allreduce"):
        garr = multihost_utils.host_local_array_to_global_array(
            jnp.asarray(raw)[None], mesh, PartitionSpec("w"))
        out = fn(garr)
        return multihost_utils.global_array_to_host_local_array(
            out, mesh, PartitionSpec())


def _cross_process_f16_allreduce(h16):
    """fp16 wire format: the explicit sharding constraint forces the
    ALL-GATHER to happen on the f16 array (half the DCN bytes), then
    the per-device sum runs in f32 — f16 wire without f16-accumulation
    overflow (a plain f16 all-reduce would sum in f16; a plain
    upcast-then-sum would put f32 on the wire)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import NamedSharding, PartitionSpec

    key = ("f16", tuple(h16.shape), _device_fingerprint())
    entry = _ALLREDUCE_CACHE.get(key)
    if entry is None:
        mesh = _per_process_mesh()
        in_s = NamedSharding(mesh, PartitionSpec("w"))
        out_s = NamedSharding(mesh, PartitionSpec())

        def f(x):
            g = jax.lax.with_sharding_constraint(x, out_s)  # gather f16
            return g.astype(jnp.float32).sum(axis=0)

        fn = jax.jit(f, in_shardings=in_s, out_shardings=out_s)
        entry = (mesh, fn)
        _ALLREDUCE_CACHE[key] = entry
    mesh, fn = entry
    with resilience.guard_collective("kvstore_f16_allreduce"):
        garr = multihost_utils.host_local_array_to_global_array(
            jnp.asarray(h16)[None], mesh, PartitionSpec("w"))
        out = fn(garr)
        return multihost_utils.global_array_to_host_local_array(
            out, mesh, PartitionSpec())


def _cross_process_compressed_allreduce(packed, n, threshold, dtype):
    """2-bit wire format: all-gather each worker's PACKED codes (uint8,
    4 grads/byte — the bytes that cross DCN), decode and sum on-device.
    Reference: GradientCompression::Quantize/Dequantize around the
    ps-lite push (src/kvstore/gradient_compression.cc)."""
    import numpy as _np

    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from .gradient_compression import GradientCompression

    key = ("2bit", int(packed.size), int(n), float(threshold), str(dtype),
           _device_fingerprint())
    entry = _ALLREDUCE_CACHE.get(key)
    if entry is None:
        mesh = _per_process_mesh()
        in_s = NamedSharding(mesh, PartitionSpec("w"))
        out_s = NamedSharding(mesh, PartitionSpec())

        fn = jax.jit(
            lambda x: GradientCompression.decode_sum(x, n, threshold,
                                                     dtype),
            in_shardings=in_s, out_shardings=out_s)
        entry = (mesh, fn)
        _ALLREDUCE_CACHE[key] = entry
    mesh, fn = entry
    with resilience.guard_collective("kvstore_2bit_allreduce"):
        garr = multihost_utils.host_local_array_to_global_array(
            jnp.asarray(packed)[None], mesh, PartitionSpec("w"))
        out = fn(garr)
        return multihost_utils.global_array_to_host_local_array(
            out, mesh, PartitionSpec())


class KVStore:
    """In-process KVStore over XLA reductions (reference:
    include/mxnet/kvstore.h)."""

    def __init__(self, kv_type="local"):
        self._type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression = None
        self._is_dist = kv_type.startswith("dist")
        if self._is_dist:
            from . import distributed

            distributed.init_from_env()

    # -- identity --------------------------------------------------------------
    @property
    def type(self):
        return self._type

    @property
    def rank(self):
        if self._is_dist:
            import jax

            return jax.process_index()
        return 0

    @property
    def num_workers(self):
        if self._is_dist:
            import jax

            return jax.process_count()
        return 1

    # -- data plane ------------------------------------------------------------
    def init(self, key, value):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                raise MXNetError(f"key {k} already initialized")
            vs = _as_list(v)
            v0 = vs[0]
            if type(v0) is NDArray:
                # own the buffer: the caller's array may later be DONATED
                # by the fused update path, which would delete a shared one
                self._store[k] = _from_jax(v0._data.copy())
            else:
                self._store[k] = v0.copy()

    def _normalize(self, key, value):
        if isinstance(key, (list, tuple)):
            return list(key), list(value)
        return [key], [value]

    def _reduce(self, key, values):
        """Sum a device-value list (reference: Comm tree/NCCL reduce) and,
        for dist types, all-reduce across processes over ICI/DCN.  With
        gradient compression active, each worker's contribution is
        quantized (with error feedback) before the exchange, and the
        2-bit wire format is an all-gather of packed codes."""
        vals = _as_list(values)
        merged = vals[0]
        for v in vals[1:]:
            merged = merged + v
        gc = self._compression
        multi = self._is_dist and self.num_workers > 1
        if gc is None:
            if multi:
                raw = merged._data if isinstance(merged, NDArray) else merged
                summed = _cross_process_allreduce(raw)
                merged = _from_jax(summed) if isinstance(merged, NDArray) \
                    else summed
            return merged
        from .ndarray.sparse import RowSparseNDArray, row_sparse_array
        if isinstance(merged, RowSparseNDArray) and not multi:
            # compact error feedback: residuals live on touched rows
            # only (quantizing the dense view would scatter threshold
            # noise into cold embedding rows), and the result stays
            # row-sparse so the lazy-row updater path is preserved
            union, q = gc.quantize_rowsparse(
                key, merged._rs_indices, merged._rs_values)
            return row_sparse_array((q, union), shape=merged.shape)
        raw = merged._data if isinstance(merged, NDArray) else merged
        if multi and gc.type == "2bit":
            packed = gc.codes(key, raw)
            summed = _cross_process_compressed_allreduce(
                packed, raw.size, gc.threshold, raw.dtype)
            summed = summed.reshape(raw.shape)
        elif multi and gc.type == "fp16":
            # f16 on the wire, f32 accumulation (overflow-safe)
            qh = gc.quantize_fp16_wire(key, raw)
            summed = _cross_process_f16_allreduce(qh).astype(raw.dtype)
        else:
            q = gc.quantize(key, raw)
            summed = _cross_process_allreduce(q) if multi else q
        return _from_jax(summed) if isinstance(merged, NDArray) else summed

    def push(self, key, value, priority=0):
        keys, values = self._normalize(key, value)
        for k, v in zip(keys, values):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            merged = self._reduce(k, v)
            stored = self._store[k]
            if self._updater is not None:
                self._updater(k, merged, stored)
            else:
                stored._set_data(merged._data)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        assert out is not None, "pull requires out="
        keys, outs = self._normalize(key, out)
        for k, o in zip(keys, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            stored = self._store[k]
            for dst in _as_list(o):
                dst._set_data(stored._data)

    def pushpull(self, key, value, out=None, priority=0):
        """Fused push+pull ≡ all-reduce (the TPU-native primitive)."""
        self.push(key, value, priority)
        if out is not None:
            self.pull(key, out, priority)

    def bucketed_pushpull(self, keys, values, outs=None, priority=0,
                          health=False):
        """Bucketed all-reduce: dense values are flattened and
        concatenated into ~MXTPU_ALLREDUCE_BUCKET_MB (default 4 MB) flat
        buckets per dtype, reduced with ONE collective per bucket, and
        split back — the reference's big-array batching
        (MXNET_KVSTORE_BIGARRAY_BOUND / NCCL coalescing) turned inside
        out for per-parameter gradient lists.

        Keys that bucketing cannot express fall back to per-key
        `pushpull`: row-sparse values, any active gradient compression
        (its error-feedback residuals are per-key), and server-side
        updaters (the update consumes each key's reduction separately).

        With ``health=True`` a fused ``numerics.grad_health`` reduction
        runs over the POST-reduce flat buckets (the already-packed
        arrays — no second pass over the per-key gradients) and the
        ``(2,)`` ``[all_finite, global_sq_norm]`` device array is
        returned for the Trainer's numerical-health guard.  Row-sparse
        fallback keys are not covered (they also bypass the fused
        optimizer step); returns None when nothing was bucketable.
        """
        from . import numerics
        from .ndarray.sparse import RowSparseNDArray

        if outs is None:
            outs = [None] * len(keys)
        gc = self._compression
        if self._updater is not None or \
                (gc is not None and not getattr(gc, "supports_bucketing",
                                                False)):
            for k, v, o in zip(keys, values, outs):
                self.pushpull(k, v, out=o, priority=priority)
            if health:
                raws = [self._store[k]._data for k in keys
                        if k in self._store
                        and not isinstance(self._store[k],
                                           RowSparseNDArray)]
                return numerics.grad_health(raws) if raws else None
            return None
        import jax.numpy as jnp

        # local device-list merge per key (the reference's Comm tree),
        # splitting off anything non-bucketable
        dense = []  # (key, merged_raw, out)
        for k, v, o in zip(keys, values, outs):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            vals = _as_list(v)
            if any(isinstance(x, RowSparseNDArray) for x in vals) or \
                    isinstance(self._store[k], RowSparseNDArray):
                self.pushpull(k, v, out=o, priority=priority)
                continue
            merged = vals[0]
            for x in vals[1:]:
                merged = merged + x
            raw = merged._data if isinstance(merged, NDArray) else merged
            dense.append((k, raw, o))
        if not dense:
            return None
        # greedy per-dtype fill up to the bucket byte budget
        budget = _bucket_bytes()
        buckets = []
        fill = {}
        for item in dense:
            raw = item[1]
            nbytes = raw.size * raw.dtype.itemsize
            dt = str(raw.dtype)
            cur = fill.get(dt)
            if cur is None or cur[1] + nbytes > budget:
                cur = [[], 0]
                buckets.append((dt, cur))
                fill[dt] = cur
            cur[0].append(item)
            cur[1] += nbytes
        multi = self._is_dist and self.num_workers > 1
        reduced_flats = []
        for dt, (items, nbytes) in buckets:
            telemetry.count("collective.bytes", nbytes)
            telemetry.count("collective.buckets")
            with profiler.annotate("bucket_pack"):
                flat = jnp.concatenate(
                    [raw.reshape(-1) for _, raw, _ in items]) \
                    if len(items) > 1 else items[0][1].reshape(-1)
            if multi:
                with profiler.annotate("allreduce"):
                    flat = _cross_process_allreduce(
                        flat, label=f"kvstore_allreduce[{dt} bucket, "
                                    f"{nbytes} bytes, {len(items)} keys]")
            if health:
                reduced_flats.append(flat)
            offset = 0
            for k, raw, o in items:
                piece = flat[offset:offset + raw.size].reshape(raw.shape)
                offset += raw.size
                self._store[k]._set_data(piece)
                if o is not None:
                    for dst in _as_list(o):
                        dst._set_data(piece)
        if health and reduced_flats:
            return numerics.grad_health(reduced_flats)
        return None

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull ONLY the requested rows as compact row-sparse arrays —
        the reference's big-embedding bandwidth optimization
        (src/kvstore/kvstore_local.h row_sparse path).  Without row_ids
        this degrades to a dense pull."""
        if row_ids is None:
            self.pull(key, out, priority)
            return
        import jax.numpy as jnp

        from .ndarray.sparse import RowSparseNDArray

        assert out is not None, "row_sparse_pull requires out="
        keys, outs = self._normalize(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) \
            else [row_ids] * len(keys)
        for k, o, rid in zip(keys, outs, rids):
            if k not in self._store:
                raise MXNetError(f"key {k} not initialized")
            stored = self._store[k]
            # coalesce duplicate ids on the HOST before any device
            # work: recommender batches repeat hot ids heavily, and a
            # device-side unique would dispatch a program just to
            # dedupe.  np.unique also sorts, which the searchsorted
            # path below requires.
            import numpy as _host_np

            rid_host = _host_np.asarray(getattr(rid, "_data", rid))
            idx = jnp.asarray(_host_np.unique(
                rid_host.astype(_host_np.int32).reshape(-1)))
            if isinstance(stored, RowSparseNDArray):
                # compact store: gather requested rows from the stored
                # parts (absent rows pull zeros) — the dense `_data`
                # view would materialize the whole table
                from .ndarray.sparse import _coalesced_parts

                si, sv = _coalesced_parts(stored)
                if int(si.shape[0]) == 0:
                    vals = jnp.zeros((int(idx.shape[0]),)
                                     + stored.shape[1:], stored.dtype)
                else:
                    pos = jnp.clip(jnp.searchsorted(si, idx), 0,
                                   int(si.shape[0]) - 1)
                    hit = si[pos] == idx
                    shape_tail = (1,) * (sv.ndim - 1)
                    vals = jnp.where(
                        hit.reshape((-1,) + shape_tail),
                        jnp.take(sv, pos, axis=0),
                        jnp.zeros((), sv.dtype))
            else:
                vals = jnp.take(stored._data, idx, axis=0)
            for dst in _as_list(o):
                if isinstance(dst, RowSparseNDArray):
                    dst._set_sparse(idx, vals)
                else:
                    dst._set_data(jnp.zeros(
                        stored.shape, vals.dtype).at[idx].set(vals))

    def broadcast(self, key, value, out=None, priority=0):
        self.init(key, value)
        if out is not None:
            self.pull(key, out, priority)

    # -- optimizer plane -------------------------------------------------------
    def set_optimizer(self, optimizer):
        """Run parameter updates "in the store" (reference: server-side
        optimizer execution, src/kvstore/kvstore_dist_server.h)."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    set_updater = _set_updater

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for distributed training"
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for distributed training"
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())

    # -- misc parity -----------------------------------------------------------
    def set_gradient_compression(self, compression_params):
        """Enable gradient compression (reference:
        src/kvstore/gradient_compression.cc): '2bit' threshold
        quantization with per-key error feedback, or 'fp16' transfer.
        The reference restricts this to device/dist stores; same here."""
        from .gradient_compression import GradientCompression

        if not (self._is_dist or self._type == "device"):
            raise MXNetError(
                "gradient compression is supported for 'device' and "
                "'dist_*' kvstore types (reference semantics)")
        self._compression = GradientCompression(compression_params)
        self._compression_params = compression_params

    def barrier(self):
        if self._is_dist and self.num_workers > 1:
            from jax.experimental import multihost_utils

            with resilience.guard_collective("kvstore_barrier"):
                multihost_utils.sync_global_devices("kvstore_barrier")

    def _send_command_to_servers(self, head, body):
        pass


def create(name="local"):
    """mx.kv.create (reference: KVStore::Create, src/kvstore/kvstore.cc)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = ("local", "device", "nccl", "local_allreduce_device",
             "local_allreduce_cpu", "dist_sync", "dist_device_sync",
             "dist_async", "dist_sync_device", "horovod")
    if name not in valid:
        raise MXNetError(f"unknown KVStore type {name}")
    return KVStore(name)
