"""Image loading and augmentation.

Reference parity: python/mxnet/image/image.py (imdecode, imread, imresize,
resize_short, center_crop, random_crop, fixed_crop, color_normalize,
HorizontalFlipAug, CastAug, CreateAugmenter, ImageIter) — the reference
decodes via OpenCV; here PIL does codec work on host and numpy does the
geometry (a C++ libjpeg-turbo fast path is the native-pipeline milestone).

Functions with the ``_np`` suffix operate on host numpy HWC uint8 arrays
(used inside data pipelines before device transfer); the un-suffixed public
API returns NDArrays for reference compatibility.
"""

from __future__ import annotations

import io as _io
import os
import random as _pyrandom

import numpy as _np

from .base import MXNetError
from .ndarray.ndarray import NDArray, _from_jax


def _to_nd(np_arr):
    import jax.numpy as jnp

    return _from_jax(jnp.asarray(np_arr))


def _to_np(img):
    if isinstance(img, NDArray):
        return img.asnumpy()
    return _np.asarray(img)


# -- codecs --------------------------------------------------------------------

def imdecode_np(buf, iscolor=1):
    """Decode compressed image bytes → HWC uint8 numpy (RGB order, matching
    the reference's default to_rgb=1)."""
    from PIL import Image

    img = Image.open(_io.BytesIO(bytes(buf)))
    if iscolor == 0:
        img = img.convert("L")
        arr = _np.asarray(img)
        return arr[:, :, None]
    img = img.convert("RGB")
    return _np.asarray(img)


def imencode(arr, quality=95, img_fmt=".jpg"):
    """Encode HWC uint8 numpy → compressed bytes."""
    from PIL import Image

    arr = _to_np(arr).astype(_np.uint8)
    if arr.ndim == 3 and arr.shape[2] == 1:
        arr = arr[:, :, 0]
    img = Image.fromarray(arr)
    out = _io.BytesIO()
    fmt = {"jpg": "JPEG", "jpeg": "JPEG", "png": "PNG"}[
        img_fmt.lstrip(".").lower()]
    img.save(out, format=fmt, quality=quality)
    return out.getvalue()


def imdecode(buf, to_rgb=1, flag=1, **kwargs):
    """Reference: mx.image.imdecode → NDArray HWC uint8."""
    return _to_nd(imdecode_np(buf, iscolor=flag))


def imread(filename, flag=1, to_rgb=1):
    with open(filename, "rb") as f:
        return imdecode(f.read(), to_rgb=to_rgb, flag=flag)


# -- geometry (numpy) ----------------------------------------------------------

def imresize_np(arr, w, h, interp=1):
    from PIL import Image

    resample = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                3: Image.NEAREST, 4: Image.LANCZOS}.get(interp,
                                                        Image.BILINEAR)
    if arr.dtype != _np.uint8:
        # PIL has no float RGB mode; resize channel-planes in mode 'F'
        arr32 = arr.astype(_np.float32)
        planes = [
            _np.asarray(Image.fromarray(arr32[:, :, c], mode="F")
                        .resize((w, h), resample))
            for c in range(arr32.shape[2])]
        return _np.stack(planes, axis=2)
    squeeze = arr.ndim == 3 and arr.shape[2] == 1
    img = Image.fromarray(arr[:, :, 0] if squeeze else arr)
    out = _np.asarray(img.resize((w, h), resample))
    if squeeze or out.ndim == 2:
        out = out[:, :, None]
    return out


def resize_short_np(arr, size, interp=2):
    h, w = arr.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize_np(arr, new_w, new_h, interp)


def fixed_crop_np(arr, x0, y0, w, h, size=None, interp=2):
    out = arr[y0:y0 + h, x0:x0 + w, :]
    if size is not None and (w, h) != size:
        out = imresize_np(out, size[0], size[1], interp)
    return out


def center_crop_np(arr, size, interp=2):
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop_np(arr, x0, y0, new_w, new_h)
    if (new_w, new_h) != tuple(size):
        out = imresize_np(out, size[0], size[1], interp)
    return out


def random_crop_np(arr, size, interp=2):
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop_np(arr, x0, y0, new_w, new_h)
    if (new_w, new_h) != tuple(size):
        out = imresize_np(out, size[0], size[1], interp)
    return out


# -- NDArray-surface wrappers (reference API) ----------------------------------

def imresize(src, w, h, interp=1):
    return _to_nd(imresize_np(_to_np(src), w, h, interp))


def resize_short(src, size, interp=2):
    return _to_nd(resize_short_np(_to_np(src), size, interp))


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    return _to_nd(fixed_crop_np(_to_np(src), x0, y0, w, h, size, interp))


def center_crop(src, size, interp=2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    return (_to_nd(center_crop_np(arr, size, interp)),
            (x0, y0, new_w, new_h))


def random_crop(src, size, interp=2):
    arr = _to_np(src)
    h, w = arr.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop_np(arr, x0, y0, new_w, new_h)
    if (new_w, new_h) != tuple(size):
        out = imresize_np(out, size[0], size[1], interp)
    return _to_nd(out), (x0, y0, new_w, new_h)


def normalize_flip_batch_np(batch_hwc, mirror, scale, mean, std, out=None):
    """Batch-level vectorized mirror + cast + scale/mean/std normalize +
    NHWC→NCHW, replacing the per-sample float copies in the record-iter
    python path.

    ``batch_hwc`` is (N, H, W, C) (typically uint8, flipped IN PLACE for
    mirrored rows); ``mirror`` a length-N bool mask (or None); ``mean`` /
    ``std`` float32 arrays broadcastable against (C, 1, 1).  Writes the
    normalized NCHW float32 batch into ``out`` (allocated when None).

    The op sequence — flip on the integer pixels, cast the whole batch to
    float32, then in-place ``*= scale``, ``-= mean``, ``/= std`` — is
    element-wise the same float32 arithmetic as the per-sample reference
    path ``(chw.astype(f32) * scale - mean) / std``, so results are
    bit-identical to it (and to the native decode kernel).
    """
    batch_hwc = _np.asarray(batch_hwc)
    n, hh, ww, cc = batch_hwc.shape
    if mirror is not None:
        mirror = _np.asarray(mirror, dtype=bool)
        if mirror.any():
            batch_hwc[mirror] = batch_hwc[mirror, :, ::-1]
    if out is None:
        out = _np.empty((n, cc, hh, ww), dtype=_np.float32)
    _np.copyto(out, batch_hwc.transpose(0, 3, 1, 2))
    out *= scale
    out -= mean
    out /= std
    return out


def color_normalize(src, mean, std=None):
    src = _to_np(src).astype(_np.float32)
    mean = _to_np(mean) if mean is not None else None
    std = _to_np(std) if std is not None else None
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return _to_nd(src)


# -- augmenter objects (reference: mx.image.Augmenter subclasses) --------------

class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return _to_nd(_to_np(src)[:, ::-1, :])
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return _to_nd(_to_np(src).astype(self.typ))


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.brightness, self.brightness)
        return _to_nd(_to_np(src).astype(_np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.contrast, self.contrast)
        arr = _to_np(src).astype(_np.float32)
        coef = _np.array([[[0.299, 0.587, 0.114]]])
        gray = (arr * coef).sum(axis=2, keepdims=True)
        mean = gray.mean()
        return _to_nd(arr * alpha + mean * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + _pyrandom.uniform(-self.saturation, self.saturation)
        arr = _to_np(src).astype(_np.float32)
        coef = _np.array([[[0.299, 0.587, 0.114]]])
        gray = (arr * coef).sum(axis=2, keepdims=True)
        return _to_nd(arr * alpha + gray * (1.0 - alpha))


class RandomGrayAug(Augmenter):
    """Convert to 3-channel grayscale with probability p (reference:
    mx.image.RandomGrayAug)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = _np.array([[0.21, 0.21, 0.21],
                              [0.72, 0.72, 0.72],
                              [0.07, 0.07, 0.07]])

    def __call__(self, src):
        if _pyrandom.random() < self.p:
            return _to_nd(_np.dot(_to_np(src).astype(_np.float32),
                                  self.mat))
        return src


class HueJitterAug(Augmenter):
    """Hue rotation in YIQ space (reference: mx.image.HueJitterAug)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = _np.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]])
        self.ityiq = _np.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]])

    def __call__(self, src):
        alpha = _pyrandom.uniform(-self.hue, self.hue)
        u = _np.cos(alpha * _np.pi)
        w = _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0],
                        [0.0, u, -w],
                        [0.0, w, u]])
        t = _np.dot(_np.dot(self.ityiq, bt), self.tyiq).T
        arr = _to_np(src).astype(_np.float32)
        return _to_nd(_np.dot(arr, t))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class LightingAug(Augmenter):
    """PCA-based RGB jitter (AlexNet-style)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = _np.asarray(eigval)
        self.eigvec = _np.asarray(eigvec)

    def __call__(self, src):
        alpha = _np.random.normal(0, self.alphastd, size=(3,))
        rgb = _np.dot(self.eigvec * alpha, self.eigval)
        return _to_nd(_to_np(src).astype(_np.float32) + rgb)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the reference's default augmenter list (reference:
    mx.image.CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness:
        auglist.append(BrightnessJitterAug(brightness))
    if contrast:
        auglist.append(ContrastJitterAug(contrast))
    if saturation:
        auglist.append(SaturationJitterAug(saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.814],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = _np.array([123.68, 116.28, 103.53])
    if std is True:
        std = _np.array([58.395, 57.12, 57.375])
    if mean is not None and len(_np.shape(mean)):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter:
    """Python-side image iterator over .rec or .lst files (reference:
    mx.image.ImageIter)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, **kwargs):
        from .io import DataBatch, DataDesc
        from . import recordio as rio

        assert path_imgrec or path_imglist
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._items = []
        if path_imgrec:
            rec = rio.MXRecordIO(path_imgrec, "r")
            while True:
                r = rec.read()
                if r is None:
                    break
                self._items.append(("rec", r))
            rec.close()
        else:
            with open(path_imglist) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    label = [float(x) for x in parts[1:-1]]
                    self._items.append(
                        ("file", (os.path.join(path_root, parts[-1]),
                                  label)))
        self.shuffle = shuffle
        if aug_list is None:
            aug_list = CreateAugmenter(data_shape=(3,) + self.data_shape[1:])
        self.auglist = aug_list
        # Split off the maximal suffix of batch-vectorizable augmenters:
        # flip/cast/normalize run once on the whole collated batch instead
        # of per sample (each per-sample call round-trips through an
        # NDArray, i.e. a device transfer per aug per sample).  Flip
        # DECISIONS are still drawn per sample inside the loop so the RNG
        # stream — and therefore every pixel — matches the unsplit path.
        split = len(aug_list)
        while split > 0 and isinstance(
                aug_list[split - 1],
                (HorizontalFlipAug, CastAug, ColorNormalizeAug)):
            split -= 1
        self._aug_head = aug_list[:split]
        self._aug_tail = aug_list[split:]
        self._order = _np.arange(len(self._items))
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        from .io import DataDesc

        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        from .io import DataDesc

        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        if self.shuffle:
            _np.random.shuffle(self._order)
        self.cur = 0

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        from .io import DataBatch
        from . import recordio as rio

        if self.cur + self.batch_size > len(self._items):
            raise StopIteration
        c, h, w = self.data_shape
        data = _np.empty((self.batch_size, c, h, w), dtype=_np.float32)
        label = _np.empty((self.batch_size, self.label_width),
                          dtype=_np.float32)
        batch_hwc = None
        mirror = _np.zeros(self.batch_size, dtype=bool)
        for i in range(self.batch_size):
            kind, item = self._items[self._order[self.cur + i]]
            if kind == "rec":
                header, payload = rio.unpack(item)
                img = _to_nd(imdecode_np(payload))
                lab = header.label
            else:
                path, lab = item
                img = imread(path)
            for aug in self._aug_head:
                img = aug(img)
            # draw here, at this sample's position in the pipeline, so the
            # RNG stream matches running the full aug list per sample
            for aug in self._aug_tail:
                if isinstance(aug, HorizontalFlipAug):
                    mirror[i] = _pyrandom.random() < aug.p
            arr = _to_np(img)
            if batch_hwc is None:
                batch_hwc = _np.empty((self.batch_size,) + arr.shape,
                                      arr.dtype)
            batch_hwc[i] = arr
            label[i] = lab if _np.ndim(lab) else [lab] * self.label_width
        batch = batch_hwc
        for aug in self._aug_tail:
            if isinstance(aug, HorizontalFlipAug):
                if mirror.any():
                    batch[mirror] = batch[mirror, :, ::-1]
            elif isinstance(aug, CastAug):
                batch = batch.astype(aug.typ)
            else:  # ColorNormalizeAug — float64 intermediate like
                   # color_normalize, single downcast at the copyto below
                batch = batch.astype(_np.float32)
                if aug.mean is not None:
                    batch = batch - _to_np(aug.mean)
                if aug.std is not None:
                    batch = batch / _to_np(aug.std)
        _np.copyto(data, batch.transpose(0, 3, 1, 2))
        self.cur += self.batch_size
        import jax.numpy as jnp

        return DataBatch(
            data=[_from_jax(jnp.asarray(data))],
            label=[_from_jax(jnp.asarray(
                label[:, 0] if self.label_width == 1 else label))],
            pad=0)


# detection pipeline (reference: python/mxnet/image/detection.py) —
# imported at module tail to avoid the circular import with
# image_detection's `from .image import ...`
from .image_detection import (CreateDetAugmenter, DetAugmenter,  # noqa: E402
                              DetBorrowAug, DetHorizontalFlipAug,
                              DetRandomCropAug, DetRandomPadAug,
                              DetRandomSelectAug, ImageDetIter)
