"""Parallelism over TPU device meshes.

NEW, TPU-first (SURVEY.md §2.5/§2.6): replaces the reference's
KVStore/NCCL/parameter-server scaling with mesh shardings + XLA collectives:

- mesh: named-axis device meshes (dp/tp/pp/sp/ep)
- ShardedTrainer: the whole training step as one compiled XLA program
- sharding: Megatron-style tensor-parallel parameter rules
- ring: ring attention + Ulysses sequence parallelism
- pipeline: GPipe-style microbatch pipelining via ppermute
- collectives: eager collective helpers + the bandwidth measurement tool
  (reference twin: tools/bandwidth)
"""

from . import collectives
from . import mesh
from .mesh import (DP, EP, PP, SP, TP, data_parallel_mesh, default_mesh,
                   make_mesh, set_default_mesh)
from . import sharding
from .sharding import (EmbeddingRules, FSDPRules, MOE_EP_RULES, PPRules,
                       ShardingRules, TRANSFORMER_TP_RULES,
                       annotate_activations, annotate_block,
                       batch_sharding, combined_rules, embedding_rules,
                       fsdp_rules, match_partition_rules, mesh_of_params,
                       param_sharding, pp_rules, shard_model)
from . import ring
from .ring import ring_attention, ulysses_attention
from . import pipeline
from .pipeline import (PipelineTrainer, pipeline_apply,
                       stack_stage_params)
from . import trainer
from .trainer import DataParallelTrainer, ShardedTrainer
