"""Pipeline parallelism.

Reference parity: the reference's only model-parallel mechanism is
``group2ctx`` device placement (SURVEY.md §2.5 — nnvm PlaceDevice pass +
example/model-parallel-lstm).  This module is the real thing, TPU-first:
GPipe-style microbatch pipelining as ONE jitted program over the mesh
``pp`` axis using shard_map + ppermute — stage transfers are point-to-point
neighbor pushes on the ICI/DCN torus.

Design: every device holds ITS stage's parameters (stacked stage-major
arrays sharded on pp); the schedule runs num_micro + num_stages - 1 ticks;
at each tick every device runs its stage on the activation it holds, then
ppermutes activations forward one stage.  This is the standard SPMD
"collective pipeline" formulation — no per-stage programs, one XLA module.
"""

from __future__ import annotations

from ..base import MXNetError
from .mesh import PP, default_mesh


def pipeline_apply(stage_fn, params_stacked, x_micro, mesh=None, axis=PP):
    """Run a pipelined forward.

    stage_fn(stage_params, x) -> y : the per-stage computation (all stages
    must share one signature/shape — the usual homogeneous-transformer
    assumption).
    params_stacked: pytree whose leaves have leading dim = n_stages,
    sharded on `axis`.
    x_micro: (n_micro, mb, ...) microbatched input, replicated.
    Returns (n_micro, mb, ...) outputs from the LAST stage (replicated).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from ._compat import shard_map
    from jax.sharding import PartitionSpec

    mesh = mesh or default_mesh()
    if mesh is None:
        raise MXNetError("pipeline_apply needs a mesh")
    n_stages = mesh.shape.get(axis, 1)
    n_micro = x_micro.shape[0]
    if n_micro < n_stages:
        raise MXNetError(
            f"pipeline needs n_micro ({n_micro}) >= n_stages "
            f"({n_stages}) to fill the pipe")

    pspec = jax.tree_util.tree_map(
        lambda _: PartitionSpec(axis), params_stacked)
    xspec = PartitionSpec()

    def local(params, xs):
        # params leaves: (1, ...) — this device's stage slice
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        n_ticks = n_micro + n_stages - 1
        mb_shape = xs.shape[1:]
        out_shape = jax.eval_shape(
            lambda p, x: stage_fn(p, x), my_params,
            jax.ShapeDtypeStruct(mb_shape, xs.dtype))
        carry_in = jnp.zeros(mb_shape, xs.dtype)
        outs = jnp.zeros((n_micro,) + tuple(out_shape.shape),
                         out_shape.dtype)
        fwd_perm = [(r, (r + 1) % n_stages) for r in range(n_stages)]

        def tick(t, state):
            carry, outs = state
            # stage 0 ingests microbatch t (when in range)
            feed_idx = jnp.clip(t, 0, n_micro - 1)
            my_in = jnp.where(stage == 0, xs[feed_idx], carry)
            y = stage_fn(my_params, my_in)
            # last stage emits microbatch (t - n_stages + 1)
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
            emit = jnp.logical_and(stage == n_stages - 1,
                                   t >= n_stages - 1)
            outs = lax.cond(
                emit,
                lambda o: o.at[out_idx].set(y.astype(outs.dtype)),
                lambda o: o, outs)
            carry = lax.ppermute(y, axis, fwd_perm)
            return carry, outs

        _, outs = lax.fori_loop(0, n_ticks, tick, (carry_in, outs))
        # the last stage holds the real outputs; broadcast to all
        outs = lax.psum(
            jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs)),
            axis)
        return outs

    fn = shard_map(local, mesh=mesh, in_specs=(pspec, xspec),
                   out_specs=xspec, check_rep=False)
    return fn(params_stacked, x_micro)


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] → one tree with stacked leaves
    (leading dim = n_stages) ready to shard on pp."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)
