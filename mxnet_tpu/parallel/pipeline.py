"""Pipeline parallelism — forward AND training.

Reference parity: the reference's only model-parallel mechanism is
``group2ctx`` device placement (SURVEY.md §2.5 — nnvm PlaceDevice pass +
example/model-parallel-lstm).  This module is the real thing, TPU-first:
GPipe-style microbatch pipelining as ONE jitted program over the mesh
``pp`` axis using shard_map + ppermute — stage transfers are point-to-point
neighbor pushes on the ICI/DCN torus.

Design: every device holds ITS stage's parameters (stacked stage-major
arrays sharded on pp); the schedule runs num_micro + num_stages - 1 ticks;
at each tick every device runs its stage on the activation it holds, then
ppermutes activations forward one stage.  This is the standard SPMD
"collective pipeline" formulation — no per-stage programs, one XLA module.

The schedule is written as a ``lax.scan``, so reverse-mode AD *derives*
the backward pipeline (activations ride the scan's saved residuals, the
ppermute transposes to the reverse neighbor push) — the GPipe backward
schedule falls out of the forward program instead of being hand-built.
``PipelineTrainer`` stacks a homogeneous Gluon stage list on the pp axis
and compiles forward + backward + optimizer into one XLA program.
"""

from __future__ import annotations

from ..base import MXNetError
from .mesh import PP, default_mesh


def _pipeline_outs(stage_fn, n_stages, n_micro, axis, params, xs):
    """shard_map-local differentiable schedule.  params leaves: (1, ...)
    = this device's stage slice; xs: (n_micro, mb, ...) replicated.
    Returns (n_micro, mb, ...) last-stage outputs (replicated)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ._compat import pvary

    my_params = jax.tree_util.tree_map(lambda p: p[0], params)
    stage = lax.axis_index(axis)
    n_ticks = n_micro + n_stages - 1
    fwd_perm = [(r, (r + 1) % n_stages) for r in range(n_stages)]
    carry0 = pvary(jnp.zeros(xs.shape[1:], xs.dtype), (axis,))
    xs = pvary(xs, (axis,))

    def tick(carry, t):
        feed_idx = jnp.clip(t, 0, n_micro - 1)
        my_in = jnp.where(stage == 0, xs[feed_idx], carry)
        y = stage_fn(my_params, my_in)
        return lax.ppermute(y, axis, fwd_perm), y

    _, ys = lax.scan(tick, carry0, jnp.arange(n_ticks))
    # microbatch m leaves the last stage at tick m + n_stages - 1
    outs = ys[n_stages - 1:]
    # only the last stage holds real outputs; broadcast to all
    return lax.psum(
        jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs)),
        axis)


def pipeline_apply(stage_fn, params_stacked, x_micro, mesh=None, axis=PP):
    """Run a pipelined forward (differentiable).

    stage_fn(stage_params, x) -> y : the per-stage computation (all stages
    must share one signature/shape — the usual homogeneous-transformer
    assumption).
    params_stacked: pytree whose leaves have leading dim = n_stages,
    sharded on `axis`.
    x_micro: (n_micro, mb, ...) microbatched input, replicated.
    Returns (n_micro, mb, ...) outputs from the LAST stage (replicated).
    """
    import jax
    from jax.sharding import PartitionSpec

    from ._compat import shard_map

    mesh = mesh or default_mesh()
    if mesh is None:
        raise MXNetError("pipeline_apply needs a mesh")
    n_stages = mesh.shape.get(axis, 1)
    n_micro = x_micro.shape[0]
    if n_micro < n_stages:
        raise MXNetError(
            f"pipeline needs n_micro ({n_micro}) >= n_stages "
            f"({n_stages}) to fill the pipe")

    pspec = jax.tree_util.tree_map(
        lambda _: PartitionSpec(axis), params_stacked)
    xspec = PartitionSpec()

    def local(params, xs):
        return _pipeline_outs(stage_fn, n_stages, n_micro, axis, params,
                              xs)

    fn = shard_map(local, mesh=mesh, in_specs=(pspec, xspec),
                   out_specs=xspec)
    return fn(params_stacked, x_micro)


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] → one tree with stacked leaves
    (leading dim = n_stages) ready to shard on pp."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


class PipelineTrainer:
    """GPipe training of a homogeneous stage list as ONE XLA program.

    The model is a list of structurally-identical Gluon blocks (or a
    (Hybrid)Sequential whose children divide evenly into such groups):
    transformer layers, the Dense towers of the reference's
    model-parallel-lstm example, etc.  Per-stage parameters are stacked
    (leading dim = n_stages) and sharded on the mesh ``pp`` axis, so each
    device holds exactly its stage; forward runs the scan schedule above,
    backward is its AD transpose (the reverse pipeline), and the
    optimizer updates each stage's shard in place — all in one jit with
    donated buffers.

    A real model needs more than the homogeneous trunk: ``prologue``
    (e.g. token embedding) runs before the pipe and ``epilogue`` (e.g.
    the MLM head) after it.  Their parameters are replicated on the pp
    axis and their compute is bulk-synchronous around the scan schedule —
    on an SPMD pp mesh every device redundantly computes them, which
    costs no wall-clock (the alternative is those devices idling) and
    keeps the scanned schedule shape-uniform, which is what lets one XLA
    program express the whole pipeline.  This pipelines a full BERT
    (embedding + N encoder layers + MLM head); see
    gluon.model_zoo.bert.bert_pipeline_parts.

    v1 limits (documented, reference has no pipeline at all): all blocks
    must be aux-free (no BatchNorm running stats) and trunk stages share
    one input/output shape; the loss attaches to the epilogue's (or last
    stage's) output.
    """

    def __init__(self, stages, loss_fn, optimizer="sgd",
                 optimizer_params=None, mesh=None, n_microbatches=None,
                 axis=PP, prologue=None, epilogue=None):
        import jax

        from .trainer import _PureOptimizer

        mesh = mesh or default_mesh()
        if mesh is None:
            raise MXNetError("PipelineTrainer needs a mesh")
        self.mesh = mesh
        self.axis = axis
        self.n_stages = mesh.shape.get(axis, 1)
        self.loss_fn = loss_fn
        self.stages = self._as_stages(stages)
        self.prologue = prologue
        self.epilogue = epilogue
        self.n_micro = int(n_microbatches or self.n_stages)
        if self.n_micro < self.n_stages:
            raise MXNetError("n_microbatches must be >= n_stages")
        opt_kwargs = dict(optimizer_params or {})
        lr = opt_kwargs.pop("learning_rate", opt_kwargs.pop("lr", 0.01))
        self.optimizer = _PureOptimizer(optimizer, lr=lr, **opt_kwargs)
        self._num_update = 0
        self._initialized = False
        self._step_fn = None

    def _as_stages(self, stages):
        if isinstance(stages, (list, tuple)):
            stage_list = list(stages)
        else:  # a Sequential-like block
            children = list(stages._children.values())
            if not children or len(children) % self.n_stages:
                raise MXNetError(
                    f"cannot split {len(children)} layers into "
                    f"{self.n_stages} equal pipeline stages")
            per = len(children) // self.n_stages
            if per == 1:
                stage_list = children
            else:
                from ..gluon.nn import HybridSequential

                stage_list = []
                for s in range(self.n_stages):
                    seq = HybridSequential(prefix=f"ppstage{s}_")
                    for c in children[s * per:(s + 1) * per]:
                        seq.add(c)
                    stage_list.append(seq)
        if len(stage_list) != self.n_stages:
            raise MXNetError(
                f"got {len(stage_list)} stages for a {self.n_stages}-way "
                f"pp mesh")
        return stage_list

    # -- staging ---------------------------------------------------------------

    def _collect_trainable(self, block, what):
        items = list(block.collect_params().items())
        bad = [n for n, p in items if p.grad_req == "null"]
        if bad:
            raise MXNetError(
                f"PipelineTrainer: aux params unsupported in v1 "
                f"({what} has {bad})")
        return items

    def _stage_params(self, example):
        """Materialize deferred shapes, stack per-stage params on pp;
        prologue/epilogue params are replicated."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        from .. import autograd as _ag
        from ..gluon.block import _TRACE

        # resolve deferred init by running the whole chain once
        prev = _TRACE.force_eager
        _TRACE.force_eager = True
        try:
            with _ag.pause():
                h = example
                if self.prologue is not None:
                    h = self.prologue(h)
                for s in self.stages:
                    h = s(h)
                if self.epilogue is not None:
                    self.epilogue(h)
        finally:
            _TRACE.force_eager = prev

        # structural (registration) order, NOT name sort: lexicographic
        # names permute across stages once indices hit two digits
        # (dense9 > dense10), mis-pairing weights between stages
        per_stage = [
            [p.data()._data for _, p in self._collect_trainable(s, "stage")]
            for s in self.stages]
        shapes = [[tuple(a.shape) for a in vals] for vals in per_stage]
        if any(sh != shapes[0] for sh in shapes[1:]):
            raise MXNetError(
                f"pipeline stages are not structurally identical: "
                f"{shapes}")
        # template ids come from stage 0; its forward executes every stage
        self._template = self.stages[0]
        self._template_ids = [id(p) for _, p in
                              self._template.collect_params().items()]
        stacked = [jnp.stack([vals[j] for vals in per_stage])
                   for j in range(len(per_stage[0]))]
        self._pspec = NamedSharding(self.mesh, PartitionSpec(self.axis))
        self._repl = NamedSharding(self.mesh, PartitionSpec())
        self._n_trunk = len(stacked)
        param_vals = [jax.device_put(a, self._pspec) for a in stacked]
        shardings = [self._pspec] * len(stacked)
        tmpl = list(self._template.collect_params().items())
        wd = [p.wd_mult for _, p in tmpl]
        lr = [p.lr_mult for _, p in tmpl]

        # prologue/epilogue: replicated leaves appended after the trunk
        self._edge_ids = {}
        for name, block in (("prologue", self.prologue),
                            ("epilogue", self.epilogue)):
            if block is None:
                self._edge_ids[name] = []
                continue
            items = self._collect_trainable(block, name)
            self._edge_ids[name] = [id(p) for _, p in items]
            param_vals += [jax.device_put(p.data()._data, self._repl)
                           for _, p in items]
            shardings += [self._repl] * len(items)
            wd += [p.wd_mult for _, p in items]
            lr += [p.lr_mult for _, p in items]

        self._param_vals = param_vals
        self._param_shardings = shardings
        self._opt_state = [
            tuple(jax.device_put(s, sh) for s in states)
            for states, sh in zip(self.optimizer.init_state(param_vals),
                                  shardings)]
        self._wd_mults = wd
        self._lr_mults = lr
        self._initialized = True

    def _build_step(self, batch_shape):
        import jax
        import jax.numpy as jnp

        from .. import autograd as _ag
        from .. import random as _random
        from ..gluon.block import _TRACE

        template = self._template
        t_ids = list(self._template_ids)
        loss_block = self.loss_fn
        optimizer = self.optimizer
        n_stages, n_micro, axis = self.n_stages, self.n_micro, self.axis
        mesh = self.mesh
        wd_mults = tuple(self._wd_mults)
        lr_mults = tuple(self._lr_mults)

        from jax.sharding import PartitionSpec

        from ._compat import shard_map

        n_trunk = self._n_trunk
        prologue, epilogue = self.prologue, self.epilogue
        pro_ids = list(self._edge_ids["prologue"])
        epi_ids = list(self._edge_ids["epilogue"])
        n_pro = len(pro_ids)

        def _run_block(block, ids, vals, x):
            pm = dict(zip(ids, vals))
            prev_map = _TRACE.param_map
            _TRACE.param_map = pm
            try:
                with _ag.train_mode():
                    return block.forward(x)
            finally:
                _TRACE.param_map = prev_map

        def stage_fn(stage_vals, x):
            return _run_block(template, t_ids, stage_vals, x)

        pspec_tree = [PartitionSpec(axis) for _ in range(n_trunk)]

        def fwd_micro(trunk_vals, xs):
            local = lambda params, xs_: _pipeline_outs(
                stage_fn, n_stages, n_micro, axis, params, xs_)
            fn = shard_map(local, mesh=mesh,
                           in_specs=(pspec_tree, PartitionSpec()),
                           out_specs=PartitionSpec())
            return fn(trunk_vals, xs)

        def pure_step(param_vals, opt_state, x, y, key, lr, t):
            def loss_of(pv):
                trunk = pv[:n_trunk]
                pro = pv[n_trunk:n_trunk + n_pro]
                epi = pv[n_trunk + n_pro:]
                with _random.key_scope(key):
                    h = x
                    if prologue is not None:
                        # replicated on pp: every device computes the
                        # embedding for the full batch (no wall-clock
                        # cost — they'd be idle), grads come out
                        # identical, optimizer updates stay replicated
                        h = _run_block(prologue, pro_ids, pro, h)
                    hs = h.reshape((n_micro, -1) + h.shape[1:])
                    outs = fwd_micro(trunk, hs)
                    outs = outs.reshape((-1,) + outs.shape[2:])
                    if epilogue is not None:
                        outs = _run_block(epilogue, epi_ids, epi, outs)
                    loss = loss_block(outs, y) \
                        if loss_block is not None else outs
                return jnp.mean(loss)

            loss, grads = jax.value_and_grad(loss_of)(param_vals)
            new_p, new_s = optimizer.apply(
                param_vals, grads, opt_state, lr, t, wd_mults, lr_mults,
                1.0)
            return new_p, new_s, loss

        with self.mesh:
            self._step_fn = jax.jit(
                pure_step,
                in_shardings=(
                    list(self._param_shardings),
                    [tuple(sh for _ in st)
                     for st, sh in zip(self._opt_state,
                                       self._param_shardings)],
                    self._repl, self._repl, None, None, None),
                out_shardings=(
                    list(self._param_shardings),
                    [tuple(sh for _ in st)
                     for st, sh in zip(self._opt_state,
                                       self._param_shardings)],
                    self._repl),
                donate_argnums=(0, 1))

    # -- public API ------------------------------------------------------------

    def step(self, data, label):
        """One pipelined training step; batch dim 0 must divide into
        n_microbatches."""
        import jax
        import jax.numpy as jnp

        from .. import random as _random
        from ..ndarray.ndarray import NDArray, _from_jax

        x = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        y = label._data if isinstance(label, NDArray) \
            else jnp.asarray(label)
        if x.shape[0] % self.n_micro:
            raise MXNetError(
                f"batch {x.shape[0]} not divisible by n_microbatches "
                f"{self.n_micro}")
        if not self._initialized:
            mb = x.shape[0] // self.n_micro
            self._stage_params(_from_jax(x[:mb]))
            self._build_step(x.shape)
        x = jax.device_put(x, self._repl)
        y = jax.device_put(y, self._repl)
        self._num_update += 1
        t = self._num_update
        lr = self.optimizer.lr_at(t)
        key = _random.next_key()
        self._param_vals, self._opt_state, loss = self._step_fn(
            self._param_vals, self._opt_state, x, y, key,
            jnp.asarray(lr, jnp.float32), jnp.asarray(t, jnp.float32))
        return _from_jax(loss)

    def sync_params(self):
        """Write stage slices (and replicated prologue/epilogue values)
        back into the Gluon Parameters."""
        for j, stacked in enumerate(self._param_vals[:self._n_trunk]):
            for s, stage in enumerate(self.stages):
                items = list(stage.collect_params().items())
                items[j][1].data()._set_data(stacked[s])
        i = self._n_trunk
        for block in (self.prologue, self.epilogue):
            if block is None:
                continue
            for _, p in block.collect_params().items():
                p.data()._set_data(self._param_vals[i])
                i += 1
