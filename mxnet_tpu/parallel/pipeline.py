"""Pipeline parallelism — forward AND training.

Reference parity: the reference's only model-parallel mechanism is
``group2ctx`` device placement (SURVEY.md §2.5 — nnvm PlaceDevice pass +
example/model-parallel-lstm).  This module is the real thing, TPU-first:
GPipe-style microbatch pipelining as ONE jitted program over the mesh
``pp`` axis using shard_map + ppermute — stage transfers are point-to-point
neighbor pushes on the ICI/DCN torus.

Design: every device holds ITS stage's parameters (stacked stage-major
arrays sharded on pp); the schedule runs num_micro + num_stages - 1 ticks;
at each tick every device runs its stage on the activation it holds, then
ppermutes activations forward one stage.  This is the standard SPMD
"collective pipeline" formulation — no per-stage programs, one XLA module.

The schedule is written as a ``lax.scan``, so reverse-mode AD *derives*
the backward pipeline (activations ride the scan's saved residuals, the
ppermute transposes to the reverse neighbor push) — the GPipe backward
schedule falls out of the forward program instead of being hand-built.
``PipelineTrainer`` stacks a homogeneous Gluon stage list on the pp axis
and compiles forward + backward + optimizer into one XLA program.
"""

from __future__ import annotations

from ..base import MXNetError
from .mesh import PP, default_mesh


def _pipeline_outs(stage_fn, n_stages, n_micro, axis, params, xs,
                   aux=None):
    """shard_map-local differentiable schedule.  params leaves: (1, ...)
    = this device's stage slice; xs: (n_micro, mb, ...) replicated.
    Returns (n_micro, mb, ...) last-stage outputs (replicated); with
    ``aux`` (this device's stage aux slice, e.g. BN running stats —
    stage_fn then has signature (params, aux, x) -> (y, new_aux))
    returns (outs, final_aux).  Aux updates are gated to the ticks where
    the stage holds REAL data — during fill/drain the stage executes on
    garbage and its stats update is discarded."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ._compat import pvary

    my_params = jax.tree_util.tree_map(lambda p: p[0], params)
    stage = lax.axis_index(axis)
    n_ticks = n_micro + n_stages - 1
    fwd_perm = [(r, (r + 1) % n_stages) for r in range(n_stages)]
    carry0 = pvary(jnp.zeros(xs.shape[1:], xs.dtype), (axis,))
    xs = pvary(xs, (axis,))

    if aux is None:
        def tick(carry, t):
            feed_idx = jnp.clip(t, 0, n_micro - 1)
            my_in = jnp.where(stage == 0, xs[feed_idx], carry)
            y = stage_fn(my_params, my_in)
            return lax.ppermute(y, axis, fwd_perm), y

        _, ys = lax.scan(tick, carry0, jnp.arange(n_ticks))
    else:
        my_aux = jax.tree_util.tree_map(lambda a: a[0], aux)

        def tick(carry, t):
            act, aux_cur = carry
            feed_idx = jnp.clip(t, 0, n_micro - 1)
            my_in = jnp.where(stage == 0, xs[feed_idx], act)
            y, aux_new = stage_fn(my_params, aux_cur, my_in)
            # stage s holds microbatch data only for s <= t < s + n_micro
            valid = (t >= stage) & (t < stage + n_micro)
            aux_cur = jax.tree_util.tree_map(
                lambda n, o: jnp.where(valid, n, o), aux_new, aux_cur)
            return (lax.ppermute(y, axis, fwd_perm), aux_cur), y

        (_, final_aux), ys = lax.scan(tick, (carry0, my_aux),
                                      jnp.arange(n_ticks))
    # microbatch m leaves the last stage at tick m + n_stages - 1
    outs = ys[n_stages - 1:]
    # only the last stage holds real outputs; broadcast to all
    outs = lax.psum(
        jnp.where(stage == n_stages - 1, outs, jnp.zeros_like(outs)),
        axis)
    if aux is None:
        return outs
    final_aux = jax.tree_util.tree_map(lambda a: a[None], final_aux)
    return outs, final_aux


def _schedule_1f1b(n_stages, n_micro):
    """Host-side greedy 1F1B schedule.

    Returns (table_f, table_b, n_ticks, bubble): (n_ticks, n_stages)
    int arrays — table_f[t, s] is the microbatch whose FORWARD stage s
    runs at tick t (−1: none), table_b likewise for backward; bubble is
    the measured idle fraction of device-ticks.  The greedy rule (do a
    ready backward, else a forward while in-flight < n_stages − s) is
    the classic non-interleaved 1F1B: in-flight activations per stage
    are bounded by n_stages (not n_micro, GPipe's bound).
    """
    S, M = n_stages, n_micro
    fwd_ready = [list(range(M))] + [[] for _ in range(S - 1)]
    bwd_ready = [[] for _ in range(S)]
    # (arrival_tick, mb) events scheduled into the future
    fwd_arrivals = [[] for _ in range(S)]
    bwd_arrivals = [[] for _ in range(S)]
    inflight = [0] * S
    done_bwd = [0] * S
    rows_f, rows_b = [], []
    t = 0
    while any(d < M for d in done_bwd):
        for s in range(S):
            fwd_ready[s] += [m for at, m in fwd_arrivals[s] if at <= t]
            fwd_arrivals[s] = [(at, m) for at, m in fwd_arrivals[s]
                               if at > t]
            bwd_ready[s] += [m for at, m in bwd_arrivals[s] if at <= t]
            bwd_arrivals[s] = [(at, m) for at, m in bwd_arrivals[s]
                               if at > t]
        row_f, row_b = [-1] * S, [-1] * S
        for s in range(S):
            if bwd_ready[s]:
                b = min(bwd_ready[s])
                bwd_ready[s].remove(b)
                row_b[s] = b
                inflight[s] -= 1
                done_bwd[s] += 1
                if s > 0:
                    bwd_arrivals[s - 1].append((t + 1, b))
            elif fwd_ready[s] and inflight[s] < S - s:
                f = min(fwd_ready[s])
                fwd_ready[s].remove(f)
                row_f[s] = f
                inflight[s] += 1
                if s < S - 1:
                    fwd_arrivals[s + 1].append((t + 1, f))
                else:
                    bwd_arrivals[s].append((t + 1, f))
            # else: bubble
        rows_f.append(row_f)
        rows_b.append(row_b)
        t += 1
        if t > 4 * (M + S) + 8:  # safety against a schedule bug
            raise MXNetError("1F1B schedule did not converge")
    n_ticks = len(rows_f)
    busy = sum(1 for row in rows_f for v in row if v >= 0) + \
        sum(1 for row in rows_b for v in row if v >= 0)
    bubble = 1.0 - busy / float(S * n_ticks)
    return rows_f, rows_b, n_ticks, bubble


def gpipe_bubble_fraction(n_stages, n_micro):
    """Analytic GPipe bubble: (S−1)/(M+S−1) per fwd/bwd pass."""
    return (n_stages - 1) / float(n_micro + n_stages - 1)


def _pipeline_1f1b_grads(stage_apply, epi_loss, n_stages, n_micro, axis,
                         tables, params, aux, epi_vals, hs, ys):
    """shard_map-local 1F1B schedule with a HAND-ROLLED backward.

    Unlike the GPipe path (AD through the fwd scan, residuals O(ticks)),
    each device keeps an S-slot activation buffer (the 1F1B in-flight
    bound) and recomputes its stage inside ``jax.vjp`` at the backward
    tick — forward and backward interleave in ONE scan, dk/cotangents
    ride reverse ppermutes, per-stage param grads accumulate locally
    (already pp-sharded).

    stage_apply(my_params, my_aux, x, key_idx) -> (y, new_aux)
    epi_loss(epi_vals, y, y_labels_mb, mb_idx) -> scalar per-mb loss
    hs, ys: (n_micro, mb, ...) replicated.
    Returns (loss, trunk_grads (1,...), epi_grads, dH (n_micro, mb, ...),
    final_aux (1,...)).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ._compat import pvary

    S, M = n_stages, n_micro
    table_f, table_b = tables
    n_ticks = table_f.shape[0]
    my_params = jax.tree_util.tree_map(lambda p: p[0], params)
    my_aux = jax.tree_util.tree_map(lambda a: a[0], aux)
    stage = lax.axis_index(axis)
    fwd_perm = [(r, (r + 1) % S) for r in range(S)]
    bwd_perm = [(r, (r - 1) % S) for r in range(S)]
    mb_shape = hs.shape[1:]
    act_dtype = hs.dtype

    def pv(x):
        return pvary(x, (axis,))

    # mark replicated epilogue params varying BEFORE they enter the
    # per-device cond: differentiating a varying computation wrt an
    # UNVARYING input makes the vjp transpose insert a psum inside the
    # branch — a collective only the last stage would execute
    # (rendezvous deadlock).  Varying-in, varying-cotangent keeps the
    # branch collective-free; the explicit psum below does the merge.
    epi_vals = jax.tree_util.tree_map(pv, list(epi_vals))

    zeros_mb = lambda: pv(jnp.zeros(mb_shape, act_dtype))
    X0 = pv(jnp.zeros((S,) + mb_shape, act_dtype))
    G0 = pv(jnp.zeros((S,) + mb_shape, act_dtype))
    dp0 = jax.tree_util.tree_map(lambda p: pv(jnp.zeros_like(p)),
                                 my_params)
    depi0 = jax.tree_util.tree_map(lambda p: pv(jnp.zeros_like(p)),
                                   list(epi_vals))
    dH0 = pv(jnp.zeros((M,) + mb_shape, act_dtype))
    hs = pv(hs)
    ys = pv(ys)

    def tick(carry, t):
        X, G, fmsg, bmsg, aux_c, dp, depi, dH, loss_acc = carry
        # receive what neighbors ppermuted at the end of tick t-1
        fl = table_f[jnp.maximum(t - 1, 0), jnp.maximum(stage - 1, 0)]
        wr_x = (t >= 1) & (stage >= 1) & (fl >= 0)
        xi = jnp.maximum(fl, 0) % S
        X = X.at[xi].set(jnp.where(wr_x, fmsg, X[xi]))
        br = table_b[jnp.maximum(t - 1, 0),
                     jnp.minimum(stage + 1, S - 1)]
        wr_g = (t >= 1) & (stage < S - 1) & (br >= 0)
        gi = jnp.maximum(br, 0) % S
        G = G.at[gi].set(jnp.where(wr_g, bmsg, G[gi]))

        f = table_f[t, stage]
        b = table_b[t, stage]
        fc = jnp.clip(f, 0, M - 1)
        bc = jnp.clip(b, 0, M - 1)
        x_in = jnp.where(stage == 0, hs[fc], X[fc % S])
        x_res = jnp.where(stage == 0, hs[bc], X[bc % S])

        def do_fwd(_):
            y, aux_new = stage_apply(my_params, aux_c, x_in,
                                     fc * S + stage)
            return y.astype(act_dtype), aux_new

        def skip_fwd(_):
            return zeros_mb(), aux_c

        y_out, aux_c = lax.cond(f >= 0, do_fwd, skip_fwd, None)

        def do_bwd(_):
            def last(_):
                def f2(p, x, ev):
                    y2, _ = stage_apply(p, aux_c, x, bc * S + stage)
                    return epi_loss(ev, y2, ys[bc], bc)

                lval, vjp = jax.vjp(f2, my_params, x_res, epi_vals)
                dp_b, dx_b, depi_b = vjp(
                    pv(jnp.asarray(1.0 / M, lval.dtype)))
                return (jax.tree_util.tree_map(pv, dp_b),
                        pv(dx_b.astype(act_dtype)),
                        jax.tree_util.tree_map(pv, list(depi_b)),
                        pv((lval / M).astype(jnp.float32)))

            def mid(_):
                dy = G[bc % S]

                def f3(p, x):
                    y2, _ = stage_apply(p, aux_c, x, bc * S + stage)
                    return y2.astype(act_dtype)

                _, vjp = jax.vjp(f3, my_params, x_res)
                dp_b, dx_b = vjp(dy)
                return jax.tree_util.tree_map(pv, dp_b), \
                    pv(dx_b.astype(act_dtype)), \
                    jax.tree_util.tree_map(
                        lambda z: pv(jnp.zeros_like(z)),
                        list(epi_vals)), \
                    pv(jnp.asarray(0.0, jnp.float32))

            return lax.cond(stage == S - 1, last, mid, None)

        def skip_bwd(_):
            zt = lambda tree: jax.tree_util.tree_map(
                lambda z: pv(jnp.zeros_like(z)), tree)
            return (zt(my_params), zeros_mb(), zt(list(epi_vals)),
                    pv(jnp.asarray(0.0, jnp.float32)))

        dp_b, dx_b, depi_b, lval = lax.cond(b >= 0, do_bwd, skip_bwd,
                                            None)
        dp = jax.tree_util.tree_map(jnp.add, dp, dp_b)
        depi = jax.tree_util.tree_map(jnp.add, depi, depi_b)
        loss_acc = loss_acc + lval
        take = ((stage == 0) & (b >= 0)).astype(dH.dtype)
        dH = dH.at[bc].add(take * dx_b)
        bmsg_new = jnp.where(stage > 0, dx_b, jnp.zeros_like(dx_b))
        fmsg_new = lax.ppermute(y_out, axis, fwd_perm)
        bmsg_new = lax.ppermute(bmsg_new, axis, bwd_perm)
        return (X, G, fmsg_new, bmsg_new, aux_c, dp, depi, dH,
                loss_acc), None

    carry0 = (X0, G0, zeros_mb(), zeros_mb(), my_aux, dp0, depi0, dH0,
              pv(jnp.asarray(0.0, jnp.float32)))
    (X, G, _, _, aux_f, dp, depi, dH, loss_acc), _ = lax.scan(
        tick, carry0, jnp.arange(n_ticks))

    loss = lax.psum(loss_acc, axis)      # only the last stage adds loss
    dH = lax.psum(dH, axis)              # only stage 0 writes dH
    depi = jax.tree_util.tree_map(lambda g: lax.psum(g, axis), depi)
    dp = jax.tree_util.tree_map(lambda g: g[None], dp)
    aux_f = jax.tree_util.tree_map(lambda a: a[None], aux_f)
    return loss, dp, depi, dH, aux_f


def pipeline_apply(stage_fn, params_stacked, x_micro, mesh=None, axis=PP):
    """Run a pipelined forward (differentiable).

    stage_fn(stage_params, x) -> y : the per-stage computation (all stages
    must share one signature/shape — the usual homogeneous-transformer
    assumption).
    params_stacked: pytree whose leaves have leading dim = n_stages,
    sharded on `axis`.
    x_micro: (n_micro, mb, ...) microbatched input, replicated.
    Returns (n_micro, mb, ...) outputs from the LAST stage (replicated).
    """
    import jax
    from jax.sharding import PartitionSpec

    from ._compat import shard_map

    mesh = mesh or default_mesh()
    if mesh is None:
        raise MXNetError("pipeline_apply needs a mesh")
    n_stages = mesh.shape.get(axis, 1)
    n_micro = x_micro.shape[0]
    if n_micro < n_stages:
        raise MXNetError(
            f"pipeline needs n_micro ({n_micro}) >= n_stages "
            f"({n_stages}) to fill the pipe")

    pspec = jax.tree_util.tree_map(
        lambda _: PartitionSpec(axis), params_stacked)
    xspec = PartitionSpec()

    def local(params, xs):
        return _pipeline_outs(stage_fn, n_stages, n_micro, axis, params,
                              xs)

    fn = shard_map(local, mesh=mesh, in_specs=(pspec, xspec),
                   out_specs=xspec)
    return fn(params_stacked, x_micro)


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] → one tree with stacked leaves
    (leading dim = n_stages) ready to shard on pp."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


class PipelineTrainer:
    """GPipe training of a homogeneous stage list as ONE XLA program.

    The model is a list of structurally-identical Gluon blocks (or a
    (Hybrid)Sequential whose children divide evenly into such groups):
    transformer layers, the Dense towers of the reference's
    model-parallel-lstm example, etc.  Per-stage parameters are stacked
    (leading dim = n_stages) and sharded on the mesh ``pp`` axis, so each
    device holds exactly its stage; forward runs the scan schedule above,
    backward is its AD transpose (the reverse pipeline), and the
    optimizer updates each stage's shard in place — all in one jit with
    donated buffers.

    A real model needs more than the homogeneous trunk: ``prologue``
    (e.g. token embedding) runs before the pipe and ``epilogue`` (e.g.
    the MLM head) after it.  Their parameters are replicated on the pp
    axis and their compute is bulk-synchronous around the scan schedule —
    on an SPMD pp mesh every device redundantly computes them, which
    costs no wall-clock (the alternative is those devices idling) and
    keeps the scanned schedule shape-uniform, which is what lets one XLA
    program express the whole pipeline.  This pipelines a full BERT
    (embedding + N encoder layers + MLM head); see
    gluon.model_zoo.bert.bert_pipeline_parts.

    Aux state (BatchNorm running stats) is supported: per-stage aux is
    stacked on pp like the trainable params, threaded through the scan
    carry with updates gated to real-data ticks, and excluded from the
    optimizer — so BN-bearing towers (ResNet!) pipeline.  Remaining v1
    limits (documented, reference has no pipeline at all): trunk stages
    share one input/output shape; the loss attaches to the epilogue's
    (or last stage's) output.
    """

    def __init__(self, stages, loss_fn, optimizer="sgd",
                 optimizer_params=None, mesh=None, n_microbatches=None,
                 axis=PP, prologue=None, epilogue=None,
                 schedule="gpipe"):
        import jax

        from .trainer import _PureOptimizer

        mesh = mesh or default_mesh()
        if mesh is None:
            raise MXNetError("PipelineTrainer needs a mesh")
        if schedule not in ("gpipe", "1f1b"):
            raise MXNetError(
                f"PipelineTrainer: unknown schedule {schedule!r} "
                "('gpipe' or '1f1b')")
        self.mesh = mesh
        self.axis = axis
        self.n_stages = mesh.shape.get(axis, 1)
        self.loss_fn = loss_fn
        self.stages = self._as_stages(stages)
        self.prologue = prologue
        self.epilogue = epilogue
        self.schedule = schedule
        self.n_micro = int(n_microbatches or self.n_stages)
        if self.n_micro < self.n_stages:
            raise MXNetError("n_microbatches must be >= n_stages")
        if schedule == "1f1b":
            self._1f1b_tables = _schedule_1f1b(self.n_stages,
                                               self.n_micro)
            self.bubble_fraction = self._1f1b_tables[3]
            self.schedule_ticks = self._1f1b_tables[2]
        else:
            self.bubble_fraction = gpipe_bubble_fraction(self.n_stages,
                                                         self.n_micro)
            # fwd scan + its AD transpose
            self.schedule_ticks = 2 * (self.n_micro + self.n_stages - 1)
        opt_kwargs = dict(optimizer_params or {})
        lr = opt_kwargs.pop("learning_rate", opt_kwargs.pop("lr", 0.01))
        self.optimizer = _PureOptimizer(optimizer, lr=lr, **opt_kwargs)
        self._num_update = 0
        self._initialized = False
        self._step_fn = None

    def _as_stages(self, stages):
        if isinstance(stages, (list, tuple)):
            stage_list = list(stages)
        else:  # a Sequential-like block
            children = list(stages._children.values())
            if not children or len(children) % self.n_stages:
                raise MXNetError(
                    f"cannot split {len(children)} layers into "
                    f"{self.n_stages} equal pipeline stages")
            per = len(children) // self.n_stages
            if per == 1:
                stage_list = children
            else:
                from ..gluon.nn import HybridSequential

                stage_list = []
                for s in range(self.n_stages):
                    seq = HybridSequential(prefix=f"ppstage{s}_")
                    for c in children[s * per:(s + 1) * per]:
                        seq.add(c)
                    stage_list.append(seq)
        if len(stage_list) != self.n_stages:
            raise MXNetError(
                f"got {len(stage_list)} stages for a {self.n_stages}-way "
                f"pp mesh")
        return stage_list

    # -- staging ---------------------------------------------------------------

    @staticmethod
    def _split_params(block):
        """(trainable items, aux items) in structural order."""
        items = list(block.collect_params().items())
        return ([(n, p) for n, p in items if p.grad_req != "null"],
                [(n, p) for n, p in items if p.grad_req == "null"])

    def _stage_params(self, example):
        """Materialize deferred shapes, stack per-stage params on pp;
        prologue/epilogue params are replicated.  Aux params (BN running
        stats) are stacked/replicated the same way but live outside the
        optimizer — they update through the aux_collector protocol."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        from .. import autograd as _ag
        from ..gluon.block import _TRACE

        # resolve deferred init by running the whole chain once
        prev = _TRACE.force_eager
        _TRACE.force_eager = True
        try:
            with _ag.pause():
                h = example
                if self.prologue is not None:
                    h = self.prologue(h)
                for s in self.stages:
                    h = s(h)
                if self.epilogue is not None:
                    self.epilogue(h)
        finally:
            _TRACE.force_eager = prev

        # structural (registration) order, NOT name sort: lexicographic
        # names permute across stages once indices hit two digits
        # (dense9 > dense10), mis-pairing weights between stages
        split = [self._split_params(s) for s in self.stages]
        per_stage = [[p.data()._data for _, p in tr] for tr, _ in split]
        per_stage_aux = [[p.data()._data for _, p in ax]
                         for _, ax in split]
        shapes = [[tuple(a.shape) for a in vals] for vals in per_stage]
        ashapes = [[tuple(a.shape) for a in vals]
                   for vals in per_stage_aux]
        if any(sh != shapes[0] for sh in shapes[1:]) or \
                any(sh != ashapes[0] for sh in ashapes[1:]):
            raise MXNetError(
                f"pipeline stages are not structurally identical: "
                f"{shapes} / aux {ashapes}")
        # template ids come from stage 0; its forward executes every stage
        self._template = self.stages[0]
        tmpl_tr, tmpl_ax = self._split_params(self._template)
        self._template_ids = [id(p) for _, p in tmpl_tr]
        self._template_aux_ids = [id(p) for _, p in tmpl_ax]
        self._template_aux_names = [p.name for _, p in tmpl_ax]
        stacked = [jnp.stack([vals[j] for vals in per_stage])
                   for j in range(len(per_stage[0]))]
        self._pspec = NamedSharding(self.mesh, PartitionSpec(self.axis))
        self._repl = NamedSharding(self.mesh, PartitionSpec())
        self._n_trunk = len(stacked)
        param_vals = [jax.device_put(a, self._pspec) for a in stacked]
        shardings = [self._pspec] * len(stacked)
        wd = [p.wd_mult for _, p in tmpl_tr]
        lr = [p.lr_mult for _, p in tmpl_tr]
        self._trunk_aux_vals = [
            jax.device_put(jnp.stack([vals[j] for vals in per_stage_aux]),
                           self._pspec)
            for j in range(len(per_stage_aux[0]))]

        # prologue/epilogue: replicated leaves appended after the trunk
        self._edge_ids = {}
        self._edge_aux = {}
        for name, block in (("prologue", self.prologue),
                            ("epilogue", self.epilogue)):
            if block is None:
                self._edge_ids[name] = []
                self._edge_aux[name] = ([], [], [])
                continue
            items, aux_items = self._split_params(block)
            self._edge_ids[name] = [id(p) for _, p in items]
            self._edge_aux[name] = (
                [id(p) for _, p in aux_items],
                [p.name for _, p in aux_items],
                [jax.device_put(p.data()._data, self._repl)
                 for _, p in aux_items])
            param_vals += [jax.device_put(p.data()._data, self._repl)
                           for _, p in items]
            shardings += [self._repl] * len(items)
            wd += [p.wd_mult for _, p in items]
            lr += [p.lr_mult for _, p in items]

        self._param_vals = param_vals
        self._param_shardings = shardings
        self._opt_state = [
            tuple(jax.device_put(s, sh) for s in states)
            for states, sh in zip(self.optimizer.init_state(param_vals),
                                  shardings)]
        self._wd_mults = wd
        self._lr_mults = lr
        self._initialized = True

    def _build_step(self, batch_shape):
        import jax
        import jax.numpy as jnp

        from .. import autograd as _ag
        from .. import random as _random
        from ..gluon.block import _TRACE

        template = self._template
        t_ids = list(self._template_ids)
        loss_block = self.loss_fn
        optimizer = self.optimizer
        n_stages, n_micro, axis = self.n_stages, self.n_micro, self.axis
        mesh = self.mesh
        wd_mults = tuple(self._wd_mults)
        lr_mults = tuple(self._lr_mults)

        from jax.sharding import PartitionSpec

        from ._compat import shard_map

        n_trunk = self._n_trunk
        prologue, epilogue = self.prologue, self.epilogue
        pro_ids = list(self._edge_ids["prologue"])
        epi_ids = list(self._edge_ids["epilogue"])
        n_pro = len(pro_ids)
        a_ids = list(self._template_aux_ids)
        a_names = list(self._template_aux_names)
        n_aux = len(a_ids)
        pro_a_ids, pro_a_names, _ = self._edge_aux["prologue"]
        epi_a_ids, epi_a_names, _ = self._edge_aux["epilogue"]

        def _run_block(block, ids, vals, x, aux_ids=(), aux_names=(),
                       aux_vals=()):
            """Run a gluon block functionally; returns (out, new_aux)
            where new_aux follows aux_names order (unchanged entries
            keep their input value)."""
            from ..gluon.block import param_override_scope

            pm = dict(zip(ids, vals))
            pm.update(zip(aux_ids, aux_vals))
            col = {}
            with param_override_scope(pm, col), _ag.train_mode():
                out = block.forward(x)
            return out, [col.get(n, v)
                         for n, v in zip(aux_names, aux_vals)]

        if n_aux:
            def stage_fn(stage_vals, stage_aux, x):
                return _run_block(template, t_ids, stage_vals, x,
                                  a_ids, a_names, stage_aux)
        else:
            def stage_fn(stage_vals, x):
                out, _ = _run_block(template, t_ids, stage_vals, x)
                return out

        pspec_tree = [PartitionSpec(axis) for _ in range(n_trunk)]
        aspec_tree = [PartitionSpec(axis) for _ in range(n_aux)]

        def fwd_micro(trunk_vals, trunk_aux, xs):
            if n_aux:
                local = lambda params, aux_, xs_: _pipeline_outs(
                    stage_fn, n_stages, n_micro, axis, params, xs_,
                    aux=aux_)
                fn = shard_map(local, mesh=mesh,
                               in_specs=(pspec_tree, aspec_tree,
                                         PartitionSpec()),
                               out_specs=(PartitionSpec(), aspec_tree))
                return fn(trunk_vals, trunk_aux, xs)
            local = lambda params, xs_: _pipeline_outs(
                stage_fn, n_stages, n_micro, axis, params, xs_)
            fn = shard_map(local, mesh=mesh,
                           in_specs=(pspec_tree, PartitionSpec()),
                           out_specs=PartitionSpec())
            return fn(trunk_vals, xs), []

        def pure_step(param_vals, opt_state, trunk_aux, pro_aux, epi_aux,
                      x, y, key, lr, t):
            def loss_of(pv):
                trunk = pv[:n_trunk]
                pro = pv[n_trunk:n_trunk + n_pro]
                epi = pv[n_trunk + n_pro:]
                with _random.key_scope(key):
                    h = x
                    pro_aux_new = list(pro_aux)
                    if prologue is not None:
                        # replicated on pp: every device computes the
                        # embedding for the full batch (no wall-clock
                        # cost — they'd be idle), grads come out
                        # identical, optimizer updates stay replicated
                        h, pro_aux_new = _run_block(
                            prologue, pro_ids, pro, h, pro_a_ids,
                            pro_a_names, pro_aux)
                    hs = h.reshape((n_micro, -1) + h.shape[1:])
                    outs, trunk_aux_new = fwd_micro(trunk, trunk_aux, hs)
                    outs = outs.reshape((-1,) + outs.shape[2:])
                    epi_aux_new = list(epi_aux)
                    if epilogue is not None:
                        outs, epi_aux_new = _run_block(
                            epilogue, epi_ids, epi, outs, epi_a_ids,
                            epi_a_names, epi_aux)
                    loss = loss_block(outs, y) \
                        if loss_block is not None else outs
                return jnp.mean(loss), (trunk_aux_new, pro_aux_new,
                                        epi_aux_new)

            (loss, new_aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(param_vals)
            new_p, new_s = optimizer.apply(
                param_vals, grads, opt_state, lr, t, wd_mults, lr_mults,
                1.0)
            return new_p, new_s, new_aux, loss

        # -- 1F1B: hand-rolled interleaved fwd/bwd schedule -------------------
        if self.schedule == "1f1b":
            if self._edge_aux["epilogue"][0]:
                raise MXNetError(
                    "schedule='1f1b' does not support aux params in the "
                    "epilogue (the per-microbatch loss vjp would need "
                    "per-tick aux merging); use schedule='gpipe'")
            rows_f, rows_b, n_ticks, _ = self._1f1b_tables
            table_f = jnp.asarray(rows_f, jnp.int32)
            table_b = jnp.asarray(rows_b, jnp.int32)

            def pure_step_1f1b(param_vals, opt_state, trunk_aux,
                               pro_aux, epi_aux, x, y, key, lr, t):
                trunk = param_vals[:n_trunk]
                pro = param_vals[n_trunk:n_trunk + n_pro]
                epi = param_vals[n_trunk + n_pro:]

                def stage_apply(p, a, xin, key_idx):
                    # per-(microbatch, stage) key: the backward tick's
                    # recompute must draw the SAME randomness (dropout)
                    # as the forward tick did
                    with _random.key_scope(jax.random.fold_in(key,
                                                              key_idx)):
                        if n_aux:
                            return stage_fn(p, a, xin)
                        return stage_fn(p, xin), []

                def epi_loss(ev, yout, y_lbl, mb_idx):
                    with _random.key_scope(
                            jax.random.fold_in(key, 1000003 + mb_idx)):
                        out = yout
                        if epilogue is not None:
                            out, _ = _run_block(epilogue, epi_ids, ev,
                                                yout)
                        l = loss_block(out, y_lbl) \
                            if loss_block is not None else out
                        return jnp.mean(l)

                pro_aux_new = list(pro_aux)
                if prologue is not None:
                    def pro_fwd(pv_):
                        with _random.key_scope(key):
                            return _run_block(
                                prologue, pro_ids, pv_, x, pro_a_ids,
                                pro_a_names, pro_aux)
                    (h, pro_aux_new), pro_vjp = jax.vjp(pro_fwd, pro,
                                                        has_aux=False)
                else:
                    h, pro_vjp = x, None
                hs = h.reshape((n_micro, -1) + h.shape[1:])
                ys = y.reshape((n_micro, -1) + y.shape[1:])

                def local(params, aux_, epi_, hs_, ys_):
                    return _pipeline_1f1b_grads(
                        stage_apply, epi_loss, n_stages, n_micro, axis,
                        (table_f, table_b), params, aux_, epi_, hs_,
                        ys_)

                fn = shard_map(
                    local, mesh=mesh,
                    in_specs=(pspec_tree, aspec_tree,
                              [PartitionSpec()] * len(epi_ids),
                              PartitionSpec(), PartitionSpec()),
                    out_specs=(PartitionSpec(), pspec_tree,
                               [PartitionSpec()] * len(epi_ids),
                               PartitionSpec(), aspec_tree))
                loss, trunk_g, epi_g, dH, trunk_aux_new = fn(
                    trunk, trunk_aux, list(epi), hs, ys)
                if prologue is not None:
                    dH_full = dH.reshape(h.shape).astype(h.dtype)
                    (pro_g,) = pro_vjp((dH_full, [jnp.zeros_like(a) for
                                                  a in pro_aux_new]))
                else:
                    pro_g = []
                grads = list(trunk_g) + list(pro_g) + list(epi_g)
                new_p, new_s = optimizer.apply(
                    param_vals, grads, opt_state, lr, t, wd_mults,
                    lr_mults, 1.0)
                return new_p, new_s, (trunk_aux_new, pro_aux_new,
                                      list(epi_aux)), loss

            pure_step = pure_step_1f1b

        aux_shardings = ([self._pspec] * n_aux,
                         [self._repl] * len(pro_a_ids),
                         [self._repl] * len(epi_a_ids))
        with self.mesh:
            self._step_fn = jax.jit(
                pure_step,
                in_shardings=(
                    list(self._param_shardings),
                    [tuple(sh for _ in st)
                     for st, sh in zip(self._opt_state,
                                       self._param_shardings)],
                    *aux_shardings,
                    self._repl, self._repl, None, None, None),
                out_shardings=(
                    list(self._param_shardings),
                    [tuple(sh for _ in st)
                     for st, sh in zip(self._opt_state,
                                       self._param_shardings)],
                    aux_shardings,
                    self._repl),
                donate_argnums=(0, 1, 2, 3, 4))

    # -- public API ------------------------------------------------------------

    def step(self, data, label):
        """One pipelined training step; batch dim 0 must divide into
        n_microbatches."""
        import jax
        import jax.numpy as jnp

        from .. import random as _random
        from ..ndarray.ndarray import NDArray, _from_jax

        x = data._data if isinstance(data, NDArray) else jnp.asarray(data)
        y = label._data if isinstance(label, NDArray) \
            else jnp.asarray(label)
        if x.shape[0] % self.n_micro:
            raise MXNetError(
                f"batch {x.shape[0]} not divisible by n_microbatches "
                f"{self.n_micro}")
        if not self._initialized:
            mb = x.shape[0] // self.n_micro
            self._stage_params(_from_jax(x[:mb]))
            self._build_step(x.shape)
        x = jax.device_put(x, self._repl)
        y = jax.device_put(y, self._repl)
        self._num_update += 1
        t = self._num_update
        lr = self.optimizer.lr_at(t)
        key = _random.next_key()
        aux = (self._trunk_aux_vals, self._edge_aux["prologue"][2],
               self._edge_aux["epilogue"][2])
        (self._param_vals, self._opt_state, new_aux, loss) = \
            self._step_fn(
                self._param_vals, self._opt_state, *aux, x, y, key,
                jnp.asarray(lr, jnp.float32), jnp.asarray(t, jnp.float32))
        self._trunk_aux_vals = new_aux[0]
        self._edge_aux["prologue"] = self._edge_aux["prologue"][:2] + \
            (new_aux[1],)
        self._edge_aux["epilogue"] = self._edge_aux["epilogue"][:2] + \
            (new_aux[2],)
        return _from_jax(loss)

    def sync_params(self):
        """Write stage slices (and replicated prologue/epilogue values)
        back into the Gluon Parameters — trainable AND aux."""
        for s, stage in enumerate(self.stages):
            tr, ax = self._split_params(stage)
            for j, (_, p) in enumerate(tr):
                p.data()._set_data(self._param_vals[j][s])
            for j, (_, p) in enumerate(ax):
                p.data()._set_data(self._trunk_aux_vals[j][s])
        i = self._n_trunk
        for name, block in (("prologue", self.prologue),
                            ("epilogue", self.epilogue)):
            if block is None:
                continue
            tr, ax = self._split_params(block)
            for _, p in tr:
                p.data()._set_data(self._param_vals[i])
                i += 1
            for (_, p), v in zip(ax, self._edge_aux[name][2]):
                p.data()._set_data(v)
