"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

NEW, TPU-first (SURVEY.md §5.7: absent in the 2018-era reference, required
by the long-context BERT/NMT configs).  Two strategies over the mesh ``sp``
axis:

- **Ring attention** (Liu et al. 2023): Q stays local; K/V blocks rotate
  around the ring via ``ppermute`` while a flash-style online-softmax
  accumulator folds each block in.  Peak memory is O(T/p) per chip and the
  KV transfer overlaps the local block matmul on ICI.
- **Ulysses** (DeepSpeed-Ulysses): ``all_to_all`` reshards sequence ↔ heads
  so each chip runs FULL-sequence attention for T/p of the heads — cheaper
  collectives when head count ≥ ring size.

Both are differentiable by construction (shard_map transposes) and run on
the virtual CPU mesh for tests.
"""

from __future__ import annotations

import functools

from ..base import MXNetError
from .mesh import SP, default_mesh

_NEG_INF = -1e30


def _pvary(x, axis):
    """Mark an array as varying over `axis` inside shard_map (needed for
    scan/fori carries whose body mixes in device-dependent values)."""
    from ._compat import pvary

    return pvary(x, (axis,))


def _place(mesh, spec, *arrays):
    """Eagerly-called shard_map needs concrete inputs laid on the mesh;
    tracers (inside an enclosing jit) pass through untouched.  Returns the
    placed arrays plus an `eager` flag so the caller can un-commit its
    output (eager callers mix results with single-device arrays)."""
    import jax
    from jax.sharding import NamedSharding

    out = []
    eager = False
    for a in arrays:
        if isinstance(a, jax.core.Tracer):
            out.append(a)
        else:
            eager = True
            out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out), eager


def _uncommit(x, eager):
    """Bring an eager result back to the default device so it composes
    with ordinary single-device arrays (debug/eager path only — under jit
    the sharding stays)."""
    import jax

    if not eager or isinstance(x, jax.core.Tracer):
        return x
    import numpy as _host_np

    return jax.device_put(_host_np.asarray(x), jax.devices()[0])


def _online_block(o, l, m, s, v):
    """Fold one score block into the flash accumulator.

    o: (B,H,Tq,D) weighted sum; l: (B,H,Tq) denom; m: (B,H,Tq) running max;
    s: (B,H,Tq,Tk) scores; v: (B,H,Tk,D).
    """
    import jax.numpy as jnp

    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows (all -inf): exp(-inf - -inf) would be NaN
    m_safe = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
    correction = jnp.exp(jnp.where(m <= _NEG_INF / 2, _NEG_INF,
                                   m - m_safe))
    correction = jnp.where(m <= _NEG_INF / 2, 0.0, correction)
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v)
    return o_new, l_new, m_new


def _local_scores(q, k, scale, causal, q_off, k_off):
    import jax.numpy as jnp

    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        qpos = q_off + jnp.arange(Tq)
        kpos = k_off + jnp.arange(Tk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    return s


def ring_attention(q, k, v, mesh=None, axis=SP, causal=False, scale=None):
    """Attention with the sequence dim sharded on `axis`.

    q,k,v: GLOBAL arrays (B, H, T, D) laid out with T sharded on `axis`.
    Returns the attention output with the same sharding.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from ._compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = mesh or default_mesh()
    if mesh is None:
        raise MXNetError("ring_attention needs a mesh (pass mesh= or "
                         "parallel.set_default_mesh)")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    nshards = mesh.shape.get(axis, 1)
    # compose with data parallelism: batch dim stays dp-sharded inside the
    # manual region when the mesh has a dp axis
    batch_ax = "dp" if "dp" in mesh.shape else None
    spec = PartitionSpec(batch_ax, None, axis, None)
    (q, k, v), eager = _place(mesh, spec, q, k, v)

    def local(q, k, v):
        p = nshards
        i = lax.axis_index(axis)
        B, H, Tq, D = q.shape
        o = _pvary(jnp.zeros_like(q, dtype=jnp.float32), axis)
        l = _pvary(jnp.zeros((B, H, Tq), jnp.float32), axis)
        m = _pvary(jnp.full((B, H, Tq), _NEG_INF, jnp.float32), axis)
        Tk = k.shape[2]
        perm = [(r, (r + 1) % p) for r in range(p)]

        def body(step, carry):
            o, l, m, k, v = carry
            j = (i - step) % p          # which global KV block we hold now
            s = _local_scores(q.astype(jnp.float32),
                              k.astype(jnp.float32), scale, causal,
                              i * Tq, j * Tk)
            o, l, m = _online_block(o, l, m, s, v.astype(jnp.float32))
            k = lax.ppermute(k, axis, perm)
            v = lax.ppermute(v, axis, perm)
            return o, l, m, k, v

        o, l, m, k, v = lax.fori_loop(0, p, body, (o, l, m, k, v))
        l = jnp.where(l == 0.0, 1.0, l)
        return (o / l[..., None]).astype(q.dtype)

    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return _uncommit(fn(q, k, v), eager)


def ulysses_attention(q, k, v, mesh=None, axis=SP, causal=False,
                      scale=None):
    """All-to-all head↔sequence resharding attention (DeepSpeed-Ulysses).

    q,k,v: (B, H, T, D) with T sharded on `axis`; H must be divisible by
    the axis size.
    """
    import jax.numpy as jnp
    from jax import lax
    from ._compat import shard_map
    from jax.sharding import PartitionSpec

    import jax

    mesh = mesh or default_mesh()
    if mesh is None:
        raise MXNetError("ulysses_attention needs a mesh")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    nshards = mesh.shape.get(axis, 1)
    if q.shape[1] % nshards != 0:
        raise MXNetError(
            f"ulysses: num_heads {q.shape[1]} not divisible by sp size "
            f"{nshards}")
    batch_ax = "dp" if "dp" in mesh.shape else None
    spec = PartitionSpec(batch_ax, None, axis, None)
    (q, k, v), eager = _place(mesh, spec, q, k, v)

    def local(q, k, v):
        # (B, H, T/p, D) → (B, H/p, T, D): gather sequence, scatter heads
        def seq2head(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        def head2seq(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        qf, kf, vf = seq2head(q), seq2head(k), seq2head(v)
        s = jnp.einsum("bhqd,bhkd->bhqk", qf.astype(jnp.float32),
                       kf.astype(jnp.float32)) * scale
        if causal:
            T = s.shape[-1]
            mask = jnp.tril(jnp.ones((T, T), bool))
            s = jnp.where(mask[None, None], s, _NEG_INF)
        p = jax.nn.softmax(s, axis=-1)
        of = jnp.einsum("bhqk,bhkd->bhqd", p,
                        vf.astype(jnp.float32)).astype(q.dtype)
        return head2seq(of)

    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return _uncommit(fn(q, k, v), eager)
