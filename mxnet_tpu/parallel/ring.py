"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

NEW, TPU-first (SURVEY.md §5.7: absent in the 2018-era reference, required
by the long-context BERT/NMT configs).  Two strategies over the mesh ``sp``
axis:

- **Ring attention** (Liu et al. 2023): Q stays local; K/V blocks rotate
  around the ring via ``ppermute`` while a flash-style online-softmax
  accumulator folds each block in.  Peak memory is O(T/p) per chip and the
  KV transfer overlaps the local block matmul on ICI.
- **Ulysses** (DeepSpeed-Ulysses): ``all_to_all`` reshards sequence ↔ heads
  so each chip runs FULL-sequence attention for T/p of the heads — cheaper
  collectives when head count ≥ ring size.

Both are differentiable by construction (shard_map transposes) and run on
the virtual CPU mesh for tests.
"""

from __future__ import annotations

import functools

import jax

from ..base import MXNetError
from .mesh import SP, default_mesh

_NEG_INF = -1e30


def _pvary(x, axis):
    """Mark an array as varying over `axis` inside shard_map (needed for
    scan/fori carries whose body mixes in device-dependent values)."""
    from ._compat import pvary

    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    return pvary(x, axes)


def _vma_of(x):
    """The set of mesh axes `x` varies over inside shard_map (empty
    tuple on pre-vma jax or outside a manual region)."""
    try:
        return tuple(jax.typeof(x).vma)
    except Exception:
        return ()


def _place(mesh, spec, *arrays):
    """Eagerly-called shard_map needs concrete inputs laid on the mesh;
    tracers get a device_put-as-resharding too — under eager autodiff
    (NDArray autograd → jax.vjp) the primal may be COMMITTED to a single
    context device (e.g. initialized parameters) and the implicit jit
    around shard_map rejects committed off-mesh args; the device_put
    reshards the primal onto the mesh inside the trace.  Returns the
    placed arrays plus an `eager` flag so the caller can un-commit its
    output (eager callers mix results with single-device arrays)."""
    import jax
    from jax.sharding import NamedSharding

    from ..ndarray.register import in_eager_op_trace

    sh = NamedSharding(mesh, spec)
    out = []
    eager = in_eager_op_trace()
    for a in arrays:
        if not isinstance(a, jax.core.Tracer):
            eager = True
        out.append(jax.device_put(a, sh))
    return tuple(out), eager


def _uncommit(x, eager):
    """Bring an eager result back to the default device so it composes
    with ordinary single-device arrays (debug/eager path only — under a
    real enclosing jit the sharding stays)."""
    import jax

    if not eager:
        return x
    if isinstance(x, jax.core.Tracer):
        # eager-autograd trace: reshard inside the trace
        return jax.device_put(x, jax.devices()[0])
    import numpy as _host_np

    return jax.device_put(_host_np.asarray(x), jax.devices()[0])


def _online_block(o, l, m, s, v):
    """Fold one score block into the flash accumulator.

    o: (B,H,Tq,D) weighted sum; l: (B,H,Tq) denom; m: (B,H,Tq) running max;
    s: (B,H,Tq,Tk) scores; v: (B,H,Tk,D).
    """
    import jax.numpy as jnp

    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    # guard fully-masked rows (all -inf): exp(-inf - -inf) would be NaN
    m_safe = jnp.where(m_new <= _NEG_INF / 2, 0.0, m_new)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
    correction = jnp.exp(jnp.where(m <= _NEG_INF / 2, _NEG_INF,
                                   m - m_safe))
    correction = jnp.where(m <= _NEG_INF / 2, 0.0, correction)
    l_new = l * correction + jnp.sum(p, axis=-1)
    o_new = o * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v)
    return o_new, l_new, m_new


def _local_scores(q, k, scale, causal, q_off, k_off):
    import jax.numpy as jnp

    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        Tq, Tk = q.shape[2], k.shape[2]
        qpos = q_off + jnp.arange(Tq)
        kpos = k_off + jnp.arange(Tk)
        mask = qpos[:, None] >= kpos[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    return s


# -- flash-ring: Pallas blockwise kernel per ring step --------------------------
#
# Each ring step runs the streaming flash kernel (ops/pallas_attention) on
# the local (q, rotating-KV-block) pair and merges the block's NORMALIZED
# output + logsumexp into the running accumulator with the numerically
# stable logaddexp combine — per-step HBM traffic is O(Tq/p · D), never an
# O(Tq/p × Tk/p) score tensor (VERDICT r3 Weak #2).  Backward is a second
# ring pass through the FlashAttention-2 Pallas backward kernels, each
# block recomputing p = exp(s − lse_global); dk/dv accumulators travel
# around the ring with their K/V block and arrive home after p hops.


def _ring_block_fwd(q, k, v, j, i, causal, scale, bq, bk):
    """One KV block's flash forward → (out_blk, lse_blk (B,H,Tq) f32).

    Causal at BLOCK granularity: block j<i is fully visible (plain
    kernel), j==i is the diagonal (standard in-block causal, offsets
    equal), j>i is fully masked (skipped: zero output, -inf lse)."""
    import jax.numpy as jnp
    from jax import lax

    from ..ops.pallas_attention import _flash_call

    B, H, Tq, D = q.shape
    vma = _vma_of(q)

    def _call(causal_flag):
        out, lse8 = _flash_call(q, k, v, causal_flag, scale, bq, bk,
                                vma=vma)
        return out, lse8[:, :, 0].reshape(B, H, Tq)

    if not causal:
        return _call(False)

    def full(_):
        return _call(False)

    def diag(_):
        return _call(True)

    def skip(_):
        return (_pvary(jnp.zeros(q.shape, q.dtype), vma),
                _pvary(jnp.full((B, H, Tq), _NEG_INF, jnp.float32), vma))

    idx = jnp.where(j > i, 2, jnp.where(j == i, 1, 0))
    return lax.switch(idx, [full, diag, skip], None)


def _ring_block_bwd(q, k, v, out, lse8, g, j, i, causal, scale, bq, bk):
    """One KV block's flash backward with the GLOBAL lse → (dq, dk, dv)."""
    import jax.numpy as jnp
    from jax import lax

    from ..ops.pallas_attention import _flash_bwd_call

    vma = _vma_of(q)

    def _call(causal_flag):
        return _flash_bwd_call(q, k, v, out, lse8, g, causal_flag, scale,
                               bq, bk, vma=vma)

    if not causal:
        return _call(False)

    def full(_):
        return _call(False)

    def diag(_):
        return _call(True)

    def skip(_):
        return (_pvary(jnp.zeros(q.shape, q.dtype), vma),
                _pvary(jnp.zeros(k.shape, k.dtype), vma),
                _pvary(jnp.zeros(v.shape, v.dtype), vma))

    idx = jnp.where(j > i, 2, jnp.where(j == i, 1, 0))
    return lax.switch(idx, [full, diag, skip], None)


def _ring_flash_fwd_core(q, k, v, axis, p, causal, scale, bq, bk):
    import jax.numpy as jnp
    from jax import lax

    i = lax.axis_index(axis)
    B, H, Tq, D = q.shape
    vma = _vma_of(q) or axis
    o = _pvary(jnp.zeros((B, H, Tq, D), jnp.float32), vma)
    lse = _pvary(jnp.full((B, H, Tq), _NEG_INF, jnp.float32), vma)
    perm = [(r, (r + 1) % p) for r in range(p)]

    def body(step, carry):
        o, lse, k_c, v_c = carry
        j = (i - step) % p
        o_blk, lse_blk = _ring_block_fwd(q, k_c, v_c, j, i, causal,
                                         scale, bq, bk)
        lse_new = jnp.logaddexp(lse, lse_blk)
        o = (o * jnp.exp(lse - lse_new)[..., None]
             + o_blk.astype(jnp.float32)
             * jnp.exp(lse_blk - lse_new)[..., None])
        k_c = lax.ppermute(k_c, axis, perm)
        v_c = lax.ppermute(v_c, axis, perm)
        return o, lse_new, k_c, v_c

    o, lse, _, _ = lax.fori_loop(0, p, body, (o, lse, k, v))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, axis, p, causal, scale, bq, bk):
    out, _ = _ring_flash_fwd_core(q, k, v, axis, p, causal, scale, bq, bk)
    return out


def _ring_flash_vjp_fwd(q, k, v, axis, p, causal, scale, bq, bk):
    out, lse = _ring_flash_fwd_core(q, k, v, axis, p, causal, scale, bq,
                                    bk)
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis, p, causal, scale, bq, bk, res, g):
    import jax.numpy as jnp
    from jax import lax

    from ..ops.pallas_attention import _LSE_LANES

    q, k, v, out, lse = res
    i = lax.axis_index(axis)
    B, H, Tq, D = q.shape
    lse8 = jnp.tile(lse.reshape(B * H, Tq, 1), (1, 1, _LSE_LANES))
    vma = _vma_of(q) or axis
    dq = _pvary(jnp.zeros(q.shape, jnp.float32), vma)
    dk_acc = _pvary(jnp.zeros(k.shape, jnp.float32), vma)
    dv_acc = _pvary(jnp.zeros(v.shape, jnp.float32), vma)
    perm = [(r, (r + 1) % p) for r in range(p)]

    def body(step, carry):
        dq, dk_acc, dv_acc, k_c, v_c = carry
        j = (i - step) % p
        dq_b, dk_b, dv_b = _ring_block_bwd(q, k_c, v_c, out, lse8, g, j,
                                           i, causal, scale, bq, bk)
        dq = dq + dq_b.astype(jnp.float32)
        dk_acc = dk_acc + dk_b.astype(jnp.float32)
        dv_acc = dv_acc + dv_b.astype(jnp.float32)
        k_c = lax.ppermute(k_c, axis, perm)
        v_c = lax.ppermute(v_c, axis, perm)
        dk_acc = lax.ppermute(dk_acc, axis, perm)
        dv_acc = lax.ppermute(dv_acc, axis, perm)
        return dq, dk_acc, dv_acc, k_c, v_c

    dq, dk_acc, dv_acc, _, _ = lax.fori_loop(
        0, p, body, (dq, dk_acc, dv_acc, k, v))
    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype))


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)


def ring_attention(q, k, v, mesh=None, axis=SP, causal=False, scale=None,
                   impl=None, block_q=None, block_k=None):
    """Attention with the sequence dim sharded on `axis`.

    q,k,v: GLOBAL arrays (B, H, T, D) laid out with T sharded on `axis`.
    Returns the attention output with the same sharding.

    ``impl``: None (auto: Pallas flash blocks when the local sequence is
    lane-aligned or off-TPU, else the dense-XLA online-softmax path),
    ``"flash"`` or ``"dense"`` to force.  ``block_q``/``block_k``
    override the flash tile sizes (tests use small tiles to prove the
    streaming property at modest T).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from ._compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec

    from ..ops.pallas_attention import _LANE, _block_sizes, _use_interpret

    mesh = mesh or default_mesh()
    if mesh is None:
        raise MXNetError("ring_attention needs a mesh (pass mesh= or "
                         "parallel.set_default_mesh)")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    nshards = mesh.shape.get(axis, 1)
    # compose with data parallelism: batch dim stays dp-sharded inside the
    # manual region when the mesh has a dp axis
    batch_ax = "dp" if "dp" in mesh.shape else None
    spec = PartitionSpec(batch_ax, None, axis, None)
    (q, k, v), eager = _place(mesh, spec, q, k, v)

    def local_dense(q, k, v):
        p = nshards
        i = lax.axis_index(axis)
        B, H, Tq, D = q.shape
        o = _pvary(jnp.zeros_like(q, dtype=jnp.float32), axis)
        l = _pvary(jnp.zeros((B, H, Tq), jnp.float32), axis)
        m = _pvary(jnp.full((B, H, Tq), _NEG_INF, jnp.float32), axis)
        Tk = k.shape[2]
        perm = [(r, (r + 1) % p) for r in range(p)]

        def body(step, carry):
            o, l, m, k, v = carry
            j = (i - step) % p          # which global KV block we hold now
            s = _local_scores(q.astype(jnp.float32),
                              k.astype(jnp.float32), scale, causal,
                              i * Tq, j * Tk)
            o, l, m = _online_block(o, l, m, s, v.astype(jnp.float32))
            k = lax.ppermute(k, axis, perm)
            v = lax.ppermute(v, axis, perm)
            return o, l, m, k, v

        o, l, m, k, v = lax.fori_loop(0, p, body, (o, l, m, k, v))
        l = jnp.where(l == 0.0, 1.0, l)
        return (o / l[..., None]).astype(q.dtype)

    if impl not in (None, "flash", "dense"):
        raise MXNetError(
            f"ring_attention: unknown impl {impl!r} (None, 'flash' or "
            "'dense')")
    Tloc = q.shape[2] // nshards
    flash_ok = _use_interpret() or Tloc % _LANE == 0
    if impl == "flash" and not flash_ok:
        raise MXNetError(
            f"ring_attention impl='flash': local sequence {Tloc} not "
            f"{_LANE}-aligned on TPU")
    use_flash = impl != "dense" and flash_ok
    dbq, dbk = _block_sizes(Tloc)
    bq, bk = int(block_q or dbq), int(block_k or dbk)
    if use_flash and (Tloc % bq or Tloc % bk):
        raise MXNetError(
            f"ring_attention: block sizes ({bq}, {bk}) must divide the "
            f"local sequence length {Tloc} (a non-dividing block would "
            "silently leave tail blocks unwritten)")

    def local_flash(q, k, v):
        return _ring_flash(q, k, v, axis, nshards, bool(causal),
                           float(scale), bq, bk)

    # check_vma off for INTERPRET-mode flash only: interpret pallas_call
    # inside a vma-checked manual region hits a jax-internal
    # dynamic_slice vma mismatch (the error message itself prescribes
    # check_vma=False).  On real TPU the Mosaic lowering takes the vma
    # plumbed through _flash_call's out_shapes, so the check stays on.
    fn = shard_map(local_flash if use_flash else local_dense, mesh=mesh,
                   in_specs=(spec, spec, spec), out_specs=spec,
                   check_vma=not (use_flash and _use_interpret()))
    return _uncommit(fn(q, k, v), eager)


def ulysses_attention(q, k, v, mesh=None, axis=SP, causal=False,
                      scale=None):
    """All-to-all head↔sequence resharding attention (DeepSpeed-Ulysses).

    q,k,v: (B, H, T, D) with T sharded on `axis`; H must be divisible by
    the axis size.
    """
    import jax.numpy as jnp
    from jax import lax
    from ._compat import shard_map
    from jax.sharding import PartitionSpec

    import jax

    mesh = mesh or default_mesh()
    if mesh is None:
        raise MXNetError("ulysses_attention needs a mesh")
    if scale is None:
        scale = q.shape[-1] ** -0.5
    nshards = mesh.shape.get(axis, 1)
    if q.shape[1] % nshards != 0:
        raise MXNetError(
            f"ulysses: num_heads {q.shape[1]} not divisible by sp size "
            f"{nshards}")
    batch_ax = "dp" if "dp" in mesh.shape else None
    spec = PartitionSpec(batch_ax, None, axis, None)
    (q, k, v), eager = _place(mesh, spec, q, k, v)

    from ..ops.pallas_attention import (_LANE, _use_interpret,
                                        flash_attention)

    T_full = q.shape[2]
    use_flash = _use_interpret() or T_full % _LANE == 0

    def local(q, k, v):
        # (B, H, T/p, D) → (B, H/p, T, D): gather sequence, scatter heads
        def seq2head(x):
            return lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                  tiled=True)

        def head2seq(x):
            return lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                  tiled=True)

        qf, kf, vf = seq2head(q), seq2head(k), seq2head(v)
        if use_flash:
            # full-sequence attention for T/p of the heads through the
            # streaming flash kernel (custom-vjp, so Ulysses stays
            # differentiable) — the (T × T) score matrix is never
            # resident, same long-context property as the ring path
            of = flash_attention(qf, kf, vf, causal=causal, scale=scale,
                                 vma=_vma_of(qf))
        else:
            s = jnp.einsum("bhqd,bhkd->bhqk", qf.astype(jnp.float32),
                           kf.astype(jnp.float32)) * scale
            if causal:
                T = s.shape[-1]
                mask = jnp.tril(jnp.ones((T, T), bool))
                s = jnp.where(mask[None, None], s, _NEG_INF)
            p = jax.nn.softmax(s, axis=-1)
            of = jnp.einsum("bhqk,bhkd->bhqd", p,
                            vf.astype(jnp.float32)).astype(q.dtype)
        return head2seq(of)

    # check_vma off only for interpret-mode flash (same jax-internal
    # limitation as the ring path); on TPU the vma plumbs through
    # flash_attention's out_shapes and the check stays on
    fn = shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec,
                   check_vma=not (use_flash and _use_interpret()))
    return _uncommit(fn(q, k, v), eager)
