"""Collective operations over the mesh.

Reference parity: src/kvstore/comm.h (device tree reduce), kvstore_nccl.h
(NCCL all-reduce), ps-lite push/pull — all replaced by XLA collectives over
ICI/DCN (SURVEY.md §2.6).  Two surfaces:

- in-jit primitives (``psum``/``all_gather``/... from jax.lax) used inside
  shard_map'ed code — just re-exported for discoverability;
- eager helpers operating on global arrays: each is a tiny jitted program
  so the collective compiles onto ICI (used by KVStore-on-mesh and
  tools/bandwidth).
"""

from __future__ import annotations

import functools

# in-jit collective primitives (use inside shard_map with axis names)
from jax.lax import (all_gather, all_to_all, axis_index,  # noqa: F401
                     ppermute, psum, psum_scatter)


@functools.lru_cache(maxsize=None)
def _allreduce_fn(mesh, axes):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec
    from ._compat import shard_map

    spec = PartitionSpec(axes)

    def inner(x):
        return jax.lax.psum(x, axes)

    smapped = shard_map(inner, mesh=mesh, in_specs=spec, out_specs=spec)
    return jax.jit(smapped)


def allreduce(x, mesh, axis="dp"):
    """All-reduce a global array whose leading dim is sharded on `axis`
    (the kvstore push+pull ≡ all-reduce identity)."""
    return _allreduce_fn(mesh, axis)(x)


@functools.lru_cache(maxsize=None)
def _replicated_sum_fn(mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    def inner(*xs):
        out = xs[0]
        for x in xs[1:]:
            out = out + x
        return out

    return jax.jit(inner,
                   out_shardings=NamedSharding(mesh, PartitionSpec()))


def replicated_sum(xs, mesh):
    """Sum a list of replicated global arrays into a replicated result."""
    return _replicated_sum_fn(mesh)(*xs)


def device_put_sharded_batch(array, mesh, axis="dp"):
    """Lay a host batch over the mesh data axis (the TPU-native
    split_and_load: one global array, not per-device copies)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    spec = [None] * array.ndim
    spec[0] = axis
    return jax.device_put(array,
                          NamedSharding(mesh, PartitionSpec(*spec)))


def measure_allreduce_bandwidth(mesh, size_mb=64, dtype="float32",
                                iters=10, axis="dp"):
    """Achieved all-reduce algorithmic bandwidth in GB/s (reference twin:
    tools/bandwidth/measure.py — the BASELINE 'KVStore all-reduce BW'
    metric)."""
    import time

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    n = int(size_mb * (1 << 20) // jnp.zeros((), dtype).itemsize)
    n_dev = mesh.shape.get(axis, 1)
    n = (n // n_dev) * n_dev or n_dev
    x = jax.device_put(
        jnp.ones((n,), dtype),
        NamedSharding(mesh, PartitionSpec(axis)))
    fn = _allreduce_fn(mesh, axis)
    fn(x).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        x = fn(x)
    x.block_until_ready()
    dt = (time.perf_counter() - t0) / iters
    nbytes = n * jnp.zeros((), dtype).itemsize
    # ring all-reduce moves 2*(p-1)/p of the data per chip
    algo_bytes = 2 * (n_dev - 1) / max(n_dev, 1) * nbytes
    return algo_bytes / dt / 1e9
