"""jax API compatibility shims, pinned in ONE place.

Minimum supported jax: 0.4.35 (first release with `jax.shard_map`
promoted out of experimental).  Newer jax deprecates
``jax.experimental.shard_map`` and ``lax.pvary`` — prefer the stable
spellings, fall back for older installs.
"""

from __future__ import annotations

MIN_JAX_VERSION = "0.4.35"


_SM_INFO = None  # (callable, replication-check kwarg name or None)


def _resolve_shard_map():
    global _SM_INFO
    if _SM_INFO is None:
        import inspect

        import jax

        if hasattr(jax, "shard_map"):
            fn = jax.shard_map
        else:
            from jax.experimental.shard_map import shard_map as fn
        try:
            params = inspect.signature(fn).parameters
        except (TypeError, ValueError):
            params = {}
        kw = ("check_vma" if "check_vma" in params
              else "check_rep" if "check_rep" in params else None)
        _SM_INFO = (fn, kw)
    return _SM_INFO


def shard_map(*args, **kwargs):
    fn, kw = _resolve_shard_map()
    # normalize the replication-check kwarg to whatever this jax spells it
    val = kwargs.pop("check_rep", kwargs.pop("check_vma", None))
    if val is not None and kw is not None:
        kwargs[kw] = val
    return fn(*args, **kwargs)


def pvary(x, axis_names):
    """Mark an array as varying over `axis_names` inside shard_map."""
    import jax
    from jax import lax

    try:
        if set(axis_names) <= set(jax.typeof(x).vma):
            return x  # already varying
    except Exception:
        pass
    if hasattr(lax, "pcast"):
        return lax.pcast(x, tuple(axis_names), to="varying")
    if hasattr(lax, "pvary"):
        return lax.pvary(x, tuple(axis_names))
    return x  # pre-vma jax: no varying marks needed
