"""Parameter sharding rules (tensor parallelism).

NEW, TPU-first (SURVEY.md §2.5: TP is absent in the reference).  A rule set
maps parameter-name regexes to ``PartitionSpec``s; ``pjit``/GSPMD inserts
the Megatron collectives from the annotations alone — no hand-written
all-reduces in layer code.

Megatron recipe on (out, in)-layout weights (our FullyConnected keeps the
reference layout, fully_connected.cc):
- column-parallel (shard OUTPUT dim, spec ('tp', None)): QKV projections,
  FFN up-projection, embedding vocab dim;
- row-parallel (shard INPUT dim, spec (None, 'tp')): attention output
  projection, FFN down-projection — its products need one psum, which GSPMD
  emits where the annotations meet.
"""

from __future__ import annotations

import re

from .mesh import TP


class ShardingRules:
    """Ordered (regex → PartitionSpec tuple) rules; first match wins."""

    def __init__(self, rules=(), default=()):
        self._rules = [(re.compile(p), spec) for p, spec in rules]
        self._default = tuple(default)

    def spec_for(self, name, shape=None):
        from jax.sharding import PartitionSpec

        for pat, spec in self._rules:
            if pat.search(name):
                return PartitionSpec(*spec)
        return PartitionSpec(*self._default)

    def add(self, pattern, spec):
        self._rules.append((re.compile(pattern), tuple(spec)))
        return self


# default rule set for the transformer family (gluon/model_zoo/bert.py
# parameter names)
TRANSFORMER_TP_RULES = ShardingRules(rules=[
    (r"(query|key|value|qkv)_weight$", (TP, None)),   # column-parallel
    (r"(query|key|value|qkv)_bias$", (TP,)),
    (r"proj_weight$", (None, TP)),                    # row-parallel
    (r"ffn1_weight$", (TP, None)),
    (r"ffn1_bias$", (TP,)),
    (r"ffn2_weight$", (None, TP)),
    (r"word_embed_weight$|embedding\d*_weight$", (TP, None)),
    # scanned trunk (ScanTransformerEncoder): (L, ...) stacks — layer
    # dim unsharded, same Megatron column/row split on dims 1+
    (r"qkv_stack_weight$", (None, TP, None)),
    (r"qkv_stack_bias$", (None, TP)),
    (r"proj_stack_weight$", (None, None, TP)),
    (r"ffn1_stack_weight$", (None, TP, None)),
    (r"ffn1_stack_bias$", (None, TP)),
    (r"ffn2_stack_weight$", (None, None, TP)),
], default=())

# expert parallelism: MoE expert weights shard on their leading E axis
# (gluon/contrib/moe.py MoEFFN); the router gate stays replicated so
# every ep slice routes identically
from .mesh import EP  # noqa: E402

MOE_EP_RULES = ShardingRules(rules=[
    (r"expert_ffn\d_weight$", (EP, None, None)),
    (r"expert_ffn\d_bias$", (EP, None)),
], default=())


def combined_rules(*rule_sets):
    """Merge rule sets (first match wins across the concatenation) —
    e.g. combined_rules(TRANSFORMER_TP_RULES, MOE_EP_RULES) for a
    tp×ep transformer."""
    merged = ShardingRules()
    for rs in rule_sets:
        merged._rules.extend(rs._rules)
    return merged


def annotate_block(block, rules):
    """Stamp partition_spec onto every Parameter of a block (consumed by
    ShardedTrainer when laying params over the mesh)."""
    for name, param in block.collect_params().items():
        param.partition_spec = rules.spec_for(name, param.shape)
    return block


def param_sharding(param, mesh):
    """NamedSharding for a Parameter (replicated when no spec/axis)."""
    from jax.sharding import NamedSharding, PartitionSpec

    spec = param.partition_spec
    if spec is None:
        spec = PartitionSpec()
    # drop axes the mesh doesn't have (lets the same rules run on a
    # dp-only mesh)
    cleaned = []
    for entry in tuple(spec):
        if entry is None or entry in mesh.shape:
            cleaned.append(entry)
        else:
            cleaned.append(None)
    return NamedSharding(mesh, PartitionSpec(*cleaned))
