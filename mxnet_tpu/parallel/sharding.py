"""Parameter sharding rules (tensor parallelism + FSDP).

NEW, TPU-first (SURVEY.md §2.5: TP is absent in the reference).  A rule set
maps parameter-name regexes to ``PartitionSpec``s; ``pjit``/GSPMD inserts
the Megatron collectives from the annotations alone — no hand-written
all-reduces in layer code.

Megatron recipe on (out, in)-layout weights (our FullyConnected keeps the
reference layout, fully_connected.cc):
- column-parallel (shard OUTPUT dim, spec ('tp', None)): QKV projections,
  FFN up-projection, embedding vocab dim;
- row-parallel (shard INPUT dim, spec (None, 'tp')): attention output
  projection, FFN down-projection — its products need one psum, which GSPMD
  emits where the annotations meet.

FSDP is the second mode on the same surface: `FSDPRules` is a shape-driven
rule set that shards every large-enough parameter over the DATA axis —
GSPMD then all-gathers each layer's weights inside the step program and
reduce-scatters its gradients, overlapped with the backward pass.

Resolution order (pinned by tests/test_parallel.py): FIRST MATCH WINS, in
insertion order — there is no most-specific-pattern scoring.  Put narrow
patterns before broad ones; `combined_rules(a, b)` makes every rule of
``a`` outrank every rule of ``b``.
"""

from __future__ import annotations

import os
import re

from .mesh import DP, TP


class ShardingRules:
    """Ordered (regex → PartitionSpec tuple) rules; first match wins.

    ``spec_for(name, shape=None)`` resolves a parameter name to a
    `PartitionSpec`.  The base class ignores ``shape`` (shape-aware
    subclasses like `FSDPRules` consume it); a ``shape=None`` call is
    always legal and resolves regex rules only.  When nothing matches,
    the ``default`` spec applies — ``()`` (fully replicated) unless the
    rule set was built with another default.
    """

    def __init__(self, rules=(), default=()):
        self._rules = [(re.compile(p), spec) for p, spec in rules]
        self._default = tuple(default)

    def _match(self, name, shape=None):
        """The first matching spec, or None (→ caller's default)."""
        from jax.sharding import PartitionSpec

        for pat, spec in self._rules:
            if pat.search(name):
                return PartitionSpec(*spec)
        return None

    def spec_for(self, name, shape=None):
        from jax.sharding import PartitionSpec

        spec = self._match(name, shape)
        return spec if spec is not None \
            else PartitionSpec(*self._default)

    def add(self, pattern, spec):
        self._rules.append((re.compile(pattern), tuple(spec)))
        return self


def fsdp_min_size():
    """MXTPU_FSDP_MIN_SIZE: parameters with fewer elements stay
    replicated under FSDP (biases, layernorm scales — sharding them
    buys nothing and costs a collective each)."""
    try:
        return int(os.environ.get("MXTPU_FSDP_MIN_SIZE", "1024"))
    except ValueError:
        return 1024


class FSDPRules(ShardingRules):
    """Shape-driven FSDP: shard each parameter over the data axis.

    Explicit regex ``rules`` outrank the shape heuristic (so TP rules
    can sit in front via ``combined_rules(TRANSFORMER_TP_RULES,
    fsdp_rules(mesh))`` for tp-within-fsdp layouts).  The heuristic
    shards the FIRST dimension the axis size divides; parameters with
    fewer than ``min_size`` elements (default `fsdp_min_size()`), with
    no divisible dimension, or with unknown shape stay replicated.
    """

    def __init__(self, axis=DP, axis_size=None, min_size=None,
                 rules=(), default=()):
        super().__init__(rules=rules, default=default)
        self.axis = axis
        self.axis_size = axis_size
        self.min_size = fsdp_min_size() if min_size is None \
            else int(min_size)

    def _match(self, name, shape=None):
        from jax.sharding import PartitionSpec

        spec = super()._match(name, shape)
        if spec is not None:
            return spec
        if not shape:
            return None
        n = 1
        for d in shape:
            n *= int(d)
        if n < self.min_size:
            return None
        for dim, d in enumerate(shape):
            if self.axis_size is None or \
                    (self.axis_size > 0 and d % self.axis_size == 0):
                entries = [None] * len(shape)
                entries[dim] = self.axis
                return PartitionSpec(*entries)
        return None


def fsdp_rules(mesh=None, axis=DP, axis_size=None, min_size=None,
               rules=()):
    """`FSDPRules` bound to ``mesh``'s data-axis size (divisibility is
    checked against it); with no mesh, pass ``axis_size`` directly or
    leave both None to shard dim 0 unconditionally."""
    if axis_size is None and mesh is not None:
        axis_size = mesh.shape.get(axis, 1)
    return FSDPRules(axis=axis, axis_size=axis_size, min_size=min_size,
                     rules=rules)


# default rule set for the transformer family (gluon/model_zoo/bert.py
# parameter names)
TRANSFORMER_TP_RULES = ShardingRules(rules=[
    (r"(query|key|value|qkv)_weight$", (TP, None)),   # column-parallel
    (r"(query|key|value|qkv)_bias$", (TP,)),
    (r"proj_weight$", (None, TP)),                    # row-parallel
    (r"ffn1_weight$", (TP, None)),
    (r"ffn1_bias$", (TP,)),
    (r"ffn2_weight$", (None, TP)),
    (r"word_embed_weight$|embedding\d*_weight$", (TP, None)),
    # scanned trunk (ScanTransformerEncoder): (L, ...) stacks — layer
    # dim unsharded, same Megatron column/row split on dims 1+
    (r"qkv_stack_weight$", (None, TP, None)),
    (r"qkv_stack_bias$", (None, TP)),
    (r"proj_stack_weight$", (None, None, TP)),
    (r"ffn1_stack_weight$", (None, TP, None)),
    (r"ffn1_stack_bias$", (None, TP)),
    (r"ffn2_stack_weight$", (None, None, TP)),
], default=())

# serving KV cache: stage-major (L, B, H, W, Dh) along the scanned
# trunk — heads shard on the tp axis exactly like the qkv stacks above,
# so cached keys/values stay resident with the heads that produced them
SERVING_CACHE_AXES = (None, None, TP, None, None)


def serving_cache_sharding(mesh, tp_axis=TP):
    """NamedSharding for a (L, B, H, W, Dh) serving KV cache on ``mesh``
    (None mesh → None, the single-device path)."""
    if mesh is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec

    spec = tuple(tp_axis if a == TP else a for a in SERVING_CACHE_AXES)
    return NamedSharding(mesh, PartitionSpec(*spec))


# expert parallelism: MoE expert weights shard on their leading E axis
# (gluon/contrib/moe.py MoEFFN); the router gate stays replicated so
# every ep slice routes identically
from .mesh import EP  # noqa: E402

MOE_EP_RULES = ShardingRules(rules=[
    (r"expert_ffn\d_weight$", (EP, None, None)),
    (r"expert_ffn\d_bias$", (EP, None)),
], default=())


class _CombinedRules(ShardingRules):
    """First match wins ACROSS rule sets, shape heuristics included."""

    def __init__(self, sets):
        super().__init__()
        self._sets = list(sets)

    def _match(self, name, shape=None):
        for rs in self._sets:
            spec = rs._match(name, shape)
            if spec is not None:
                return spec
        return None

    def add(self, pattern, spec):
        # appended rules have the LOWEST precedence, matching the
        # concatenation semantics
        self._sets.append(ShardingRules(rules=[(pattern, spec)]))
        return self


def combined_rules(*rule_sets):
    """Merge rule sets (first match wins across the concatenation) —
    e.g. combined_rules(TRANSFORMER_TP_RULES, MOE_EP_RULES) for a
    tp×ep transformer, or combined_rules(TRANSFORMER_TP_RULES,
    fsdp_rules(mesh)) for TP weights with an FSDP fallback.  Every
    rule (and shape heuristic) of an earlier set overrides every rule
    of a later set on conflicting names."""
    return _CombinedRules(rule_sets)


def match_partition_rules(rules, params):
    """Bulk resolution: ``{name: PartitionSpec}`` for every entry of
    ``params`` (a dict of name → Parameter / array / shape tuple) —
    the pytree-of-specs step between a rule set and `NamedSharding`
    placement."""
    specs = {}
    for name, p in params.items():
        shape = p if isinstance(p, (tuple, list)) \
            else getattr(p, "shape", None)
        specs[name] = rules.spec_for(name, shape)
    return specs


def annotate_block(block, rules):
    """Stamp partition_spec onto every Parameter of a block (consumed by
    ShardedTrainer when laying params over the mesh)."""
    for name, param in block.collect_params().items():
        param.partition_spec = rules.spec_for(name, param.shape)
    return block


def param_sharding(param, mesh):
    """NamedSharding for a Parameter (replicated when no spec/axis)."""
    from jax.sharding import NamedSharding, PartitionSpec

    spec = param.partition_spec
    if spec is None:
        spec = PartitionSpec()
    # drop axes the mesh doesn't have (lets the same rules run on a
    # dp-only mesh)
    cleaned = []
    for entry in tuple(spec):
        if entry is None or entry in mesh.shape:
            cleaned.append(entry)
        else:
            cleaned.append(None)
    return NamedSharding(mesh, PartitionSpec(*cleaned))


# -- imperative-path placement (gluon Trainer + CapturedStep) ------------------

def shard_model(block, mesh, mode="tp", rules=None, axis=DP,
                min_size=None, trainer=None):
    """Annotate AND place a gluon block's parameters over ``mesh`` —
    the imperative twin of ShardedTrainer's staging, consumed by
    `gluon.Trainer.train_step`'s captured program (gluon/captured.py).

    Two modes on one rule surface:

    - ``mode='tp'``: Megatron tensor parallelism from ``rules``
      (default `TRANSFORMER_TP_RULES`) — Dense/attention weights split
      over the ``tp`` axis; pair with
      `HybridBlock.shard_activations` / `annotate_activations` for the
      activation constraints.
    - ``mode='fsdp'``: every large-enough parameter sharded over the
      data axis (`fsdp_rules`); GSPMD gathers each layer's weights
      inside the step program and reduce-scatters its gradients.
      ``rules`` (if given) overrides the shape heuristic per name.

    Initialized parameters (and their gradient buffers) are
    `jax.device_put` onto their `NamedSharding` immediately, making
    them committed sharded arrays every later jit (CachedOp forward,
    captured step, eager grouped update) infers its layout from.
    Aux parameters (``grad_req='null'`` — BatchNorm stats) replicate.
    Also sets the process default mesh.  Returns ``{name: spec}``.

    When RE-sharding a model that already trained (an elastic gang
    reshape, or turning sharding on mid-run), pass the gluon
    ``trainer``: its existing optimizer states are committed to the
    OLD placement and must move with their weights, or the next step's
    jit sees incompatible device sets.  Fresh states (created on the
    first post-shard step) place themselves.
    """
    import jax
    from jax.sharding import PartitionSpec

    from .mesh import set_default_mesh

    if mode == "fsdp":
        base = fsdp_rules(mesh=mesh, axis=axis, min_size=min_size)
        rules = base if rules is None else combined_rules(rules, base)
    elif mode == "tp":
        rules = TRANSFORMER_TP_RULES if rules is None else rules
    else:
        raise ValueError(f"shard_model: unknown mode {mode!r} "
                         "(expected 'tp' or 'fsdp')")
    from ..gluon.parameter import DeferredInitializationError

    specs = {}
    for name, p in block.collect_params().items():
        if p.grad_req == "null":
            # aux state (BN running stats) replicates in both modes
            p.partition_spec = PartitionSpec()
        else:
            p.partition_spec = rules.spec_for(name, p.shape)
        specs[name] = p.partition_spec
        sh = param_sharding(p, mesh)
        try:
            nd = p.data()
        except DeferredInitializationError:
            continue  # spec stamps now, placement at materialization
        nd._set_data(jax.device_put(nd._data, sh))
        g = getattr(p, "_grad", None)
        if g is not None and getattr(g, "_data", None) is not None \
                and getattr(p, "_grad_stype", None) != "row_sparse":
            g._set_data(jax.device_put(g._data, sh))
    if trainer is not None:
        from ..optimizer.grouped import _place_state_like

        params = list(trainer._params)
        for upd in getattr(trainer, "_updaters", []):
            for i, st in upd.states.items():
                if st is not None and 0 <= i < len(params):
                    _place_state_like(st, params[i].data())
    set_default_mesh(mesh)
    return specs


def mesh_of_params(params):
    """The Mesh an (iterable of) gluon Parameters is laid over, or None:
    the first committed multi-device `NamedSharding` found wins.  Cheap
    attribute walking only — safe on the per-step path."""
    from jax.sharding import NamedSharding

    for p in params:
        raw = getattr(getattr(p, "_data", None), "_data", None)
        sh = getattr(raw, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.mesh.size > 1:
            return sh.mesh
    return None


def batch_sharding(mesh, dim_size=None, leading=0, axis=DP):
    """NamedSharding splitting the batch dimension (dim ``leading``)
    over the data axis — replicated when the mesh has no dp axis or
    ``dim_size`` is not divisible by it (uneven batches stay whole
    rather than tripping a GSPMD padding path the eager oracle would
    not take)."""
    from jax.sharding import NamedSharding, PartitionSpec

    size = mesh.shape.get(axis, 1)
    if size <= 1 or (dim_size is not None and dim_size % size != 0):
        return NamedSharding(mesh, PartitionSpec())
    return NamedSharding(mesh,
                         PartitionSpec(*([None] * leading + [axis])))


def constrain(x, mesh, spec):
    """`with_sharding_constraint` with the same leniency as
    `param_sharding`: axes absent from the mesh drop to None, and a
    spec longer than ``x``'s rank is a no-op (identity) instead of an
    error — so one activation annotation runs sharded and unsharded."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if mesh is None or getattr(mesh, "size", 1) <= 1:
        return x
    entries = [e if e is None
               or (e in mesh.shape and mesh.shape[e] > 1) else None
               for e in tuple(spec)]
    ndim = getattr(x, "ndim", None)
    if ndim is None or len(entries) > ndim:
        return x
    # divisibility guard per sharded dim: constraint on a non-divisible
    # dim forces GSPMD padding the eager oracle never sees
    for dim, e in enumerate(entries):
        if e is not None and x.shape[dim] % mesh.shape[e] != 0:
            entries[dim] = None
    sh = NamedSharding(mesh, PartitionSpec(*entries))
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sh)
    return jax.device_put(x, sh)


def annotate_activations(block, rules, mesh=None):
    """Walk the block tree; any HybridBlock whose NAME matches a rule
    pattern gets `shard_activations(spec, mesh)` — the rules-driven way
    to place Megatron activation constraints without touching model
    code (block names, not parameter names, are matched here)."""
    def walk(b):
        if hasattr(b, "shard_activations"):
            for pat, spec in getattr(rules, "_rules", []):
                if pat.search(getattr(b, "name", "") or ""):
                    b.shard_activations(spec, mesh)
                    break
        for child in getattr(b, "_children", {}).values():
            walk(child)

    walk(block)
    return block
