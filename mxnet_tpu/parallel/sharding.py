"""Parameter sharding rules (tensor parallelism + FSDP).

NEW, TPU-first (SURVEY.md §2.5: TP is absent in the reference).  A rule set
maps parameter-name regexes to ``PartitionSpec``s; ``pjit``/GSPMD inserts
the Megatron collectives from the annotations alone — no hand-written
all-reduces in layer code.

Megatron recipe on (out, in)-layout weights (our FullyConnected keeps the
reference layout, fully_connected.cc):
- column-parallel (shard OUTPUT dim, spec ('tp', None)): QKV projections,
  FFN up-projection, embedding vocab dim;
- row-parallel (shard INPUT dim, spec (None, 'tp')): attention output
  projection, FFN down-projection — its products need one psum, which GSPMD
  emits where the annotations meet.

FSDP is the second mode on the same surface: `FSDPRules` is a shape-driven
rule set that shards every large-enough parameter over the DATA axis —
GSPMD then all-gathers each layer's weights inside the step program and
reduce-scatters its gradients, overlapped with the backward pass.

Resolution order (pinned by tests/test_parallel.py): FIRST MATCH WINS, in
insertion order — there is no most-specific-pattern scoring.  Put narrow
patterns before broad ones; `combined_rules(a, b)` makes every rule of
``a`` outrank every rule of ``b``.
"""

from __future__ import annotations

import os
import re

from .mesh import DP, PP, TP


class ShardingRules:
    """Ordered (regex → PartitionSpec tuple) rules; first match wins.

    ``spec_for(name, shape=None)`` resolves a parameter name to a
    `PartitionSpec`.  The base class ignores ``shape`` (shape-aware
    subclasses like `FSDPRules` consume it); a ``shape=None`` call is
    always legal and resolves regex rules only.  When nothing matches,
    the ``default`` spec applies — ``()`` (fully replicated) unless the
    rule set was built with another default.

    ``composable`` rule sets (class attribute, see `PPRules`) are
    overlays: inside `combined_rules` their matches merge per-dim into
    the winning base spec instead of competing whole-spec.
    """

    composable = False

    def __init__(self, rules=(), default=()):
        self._rules = [(re.compile(p), spec) for p, spec in rules]
        self._default = tuple(default)

    def _match(self, name, shape=None):
        """The first matching spec, or None (→ caller's default)."""
        from jax.sharding import PartitionSpec

        for pat, spec in self._rules:
            if pat.search(name):
                return PartitionSpec(*spec)
        return None

    def spec_for(self, name, shape=None):
        from jax.sharding import PartitionSpec

        spec = self._match(name, shape)
        return spec if spec is not None \
            else PartitionSpec(*self._default)

    def add(self, pattern, spec):
        self._rules.append((re.compile(pattern), tuple(spec)))
        return self


def fsdp_min_size():
    """MXTPU_FSDP_MIN_SIZE: parameters with fewer elements stay
    replicated under FSDP (biases, layernorm scales — sharding them
    buys nothing and costs a collective each)."""
    try:
        return int(os.environ.get("MXTPU_FSDP_MIN_SIZE", "1024"))
    except ValueError:
        return 1024


class FSDPRules(ShardingRules):
    """Shape-driven FSDP: shard each parameter over the data axis.

    Explicit regex ``rules`` outrank the shape heuristic (so TP rules
    can sit in front via ``combined_rules(TRANSFORMER_TP_RULES,
    fsdp_rules(mesh))`` for tp-within-fsdp layouts).  The heuristic
    shards the FIRST dimension the axis size divides; parameters with
    fewer than ``min_size`` elements (default `fsdp_min_size()`), with
    no divisible dimension, or with unknown shape stay replicated.
    """

    def __init__(self, axis=DP, axis_size=None, min_size=None,
                 rules=(), default=()):
        super().__init__(rules=rules, default=default)
        self.axis = axis
        self.axis_size = axis_size
        self.min_size = fsdp_min_size() if min_size is None \
            else int(min_size)

    def _match(self, name, shape=None):
        spec = super()._match(name, shape)
        if spec is not None:
            return spec
        return self._heuristic(shape)

    def _heuristic(self, shape, avoid_dims=()):
        """The shape heuristic alone (no regex): shard the FIRST
        divisible dim not in ``avoid_dims`` — the avoidance hook lets
        `combined_rules` re-run the heuristic around dims a composable
        overlay (e.g. `PPRules`) already claimed, so pp+fsdp composes
        instead of colliding on the stack dim."""
        from jax.sharding import PartitionSpec

        if not shape:
            return None
        n = 1
        for d in shape:
            n *= int(d)
        if n < self.min_size:
            return None
        for dim, d in enumerate(shape):
            if dim in avoid_dims:
                continue
            if self.axis_size is None or \
                    (self.axis_size > 0 and d % self.axis_size == 0):
                entries = [None] * len(shape)
                entries[dim] = self.axis
                return PartitionSpec(*entries)
        return None

    def _match_detail(self, name, shape=None):
        """(spec, from_heuristic) — `combined_rules` uses the flag to
        decide whether a same-dim overlay claim is a hard conflict (an
        explicit regex said so) or a re-route (heuristic moves over)."""
        spec = super()._match(name, shape)
        if spec is not None:
            return spec, False
        return self._heuristic(shape), True


def fsdp_rules(mesh=None, axis=DP, axis_size=None, min_size=None,
               rules=()):
    """`FSDPRules` bound to ``mesh``'s data-axis size (divisibility is
    checked against it); with no mesh, pass ``axis_size`` directly or
    leave both None to shard dim 0 unconditionally."""
    if axis_size is None and mesh is not None:
        axis_size = mesh.shape.get(axis, 1)
    return FSDPRules(axis=axis, axis_size=axis_size, min_size=min_size,
                     rules=rules)


class PPRules(ShardingRules):
    """Pipeline-stage partitioning of the scanned trunk: a COMPOSABLE
    overlay claiming the leading (layer-stack) dimension of every
    ``*_stack_*`` parameter for the ``pp`` axis.

    `combined_rules(PPRules(...), TRANSFORMER_TP_RULES)` merges the
    claim per-dim into the base spec — ``qkv_stack_weight`` resolves to
    ``('pp', 'tp', None)`` — rather than competing whole-spec; two sets
    assigning DIFFERENT axes to the same dim of the same param is a
    hard ValueError.  ``axis_size`` (bound via `pp_rules(mesh)`) guards
    divisibility: a stack whose layer count the stage count does not
    divide stays unclaimed rather than forcing GSPMD padding.
    """

    composable = True

    def __init__(self, axis=PP, axis_size=None, pattern=r"_stack_",
                 rules=None):
        if rules is None:
            rules = [(pattern, (axis,))]
        super().__init__(rules=rules)
        self.axis = axis
        self.axis_size = axis_size

    def _match(self, name, shape=None):
        spec = super()._match(name, shape)
        if spec is None:
            return None
        if self.axis_size and self.axis_size > 1 and shape:
            for dim, e in enumerate(tuple(spec)):
                if e is not None and (dim >= len(shape)
                                      or shape[dim] % self.axis_size):
                    return None
        return spec


def pp_rules(mesh=None, axis=PP, axis_size=None, pattern=r"_stack_"):
    """`PPRules` bound to ``mesh``'s pp-axis size (stack-length
    divisibility is checked against it); with no mesh, pass
    ``axis_size`` directly or leave both None to claim unconditionally."""
    if axis_size is None and mesh is not None:
        axis_size = mesh.shape.get(axis, 1)
    return PPRules(axis=axis, axis_size=axis_size, pattern=pattern)


class EmbeddingRules(ShardingRules):
    """Row-shard `embedding.ShardedEmbedding` tables: a COMPOSABLE
    overlay claiming dim 0 (the vocab dim) of every ``*_embed_table``
    parameter for ``axis`` (default the data axis).

    Row sharding is the memory play for recommender-scale tables — the
    vocab dim is the one that reaches hundreds of millions — and the
    data axis is where the memory is: dp ranks otherwise hold identical
    replicas.  The claim merges per-dim with TP/PP sets (PR 17), so an
    explicit column rule on the output dim coexists: ('dp', 'tp').
    Tables are named ``embed_table`` precisely so the
    ``embedding\\d*_weight`` column-parallel rule in
    `TRANSFORMER_TP_RULES` does not capture them whole-spec first.

    No divisibility guard at the RULE level — the claim always lands,
    so the spec stays stable while a deferred-init table's vocab is
    still unknown.  Divisibility is `param_sharding`'s problem: a
    committed placement cannot be uneven (jax.device_put rejects it),
    so a vocab the axis does not divide degrades that dim to None
    (replicated) at placement time, per mesh — the same table row-
    shards on one layout and replicates on another, and the elastic
    checkpoint plane carries it bitwise between the two.
    """

    composable = True

    def __init__(self, axis=DP, pattern=r"_embed_table$"):
        super().__init__(rules=[(pattern, (axis,))])
        self.axis = axis


def embedding_rules(axis=DP, pattern=r"_embed_table$"):
    """`EmbeddingRules` — named constructor for symmetry with
    `fsdp_rules` / `pp_rules` (no mesh binding needed: there is no
    divisibility guard to size)."""
    return EmbeddingRules(axis=axis, pattern=pattern)


# default rule set for the transformer family (gluon/model_zoo/bert.py
# parameter names)
TRANSFORMER_TP_RULES = ShardingRules(rules=[
    (r"(query|key|value|qkv)_weight$", (TP, None)),   # column-parallel
    (r"(query|key|value|qkv)_bias$", (TP,)),
    (r"proj_weight$", (None, TP)),                    # row-parallel
    (r"ffn1_weight$", (TP, None)),
    (r"ffn1_bias$", (TP,)),
    (r"ffn2_weight$", (None, TP)),
    (r"word_embed_weight$|embedding\d*_weight$", (TP, None)),
    # scanned trunk (ScanTransformerEncoder): (L, ...) stacks — layer
    # dim unsharded, same Megatron column/row split on dims 1+
    (r"qkv_stack_weight$", (None, TP, None)),
    (r"qkv_stack_bias$", (None, TP)),
    (r"proj_stack_weight$", (None, None, TP)),
    (r"ffn1_stack_weight$", (None, TP, None)),
    (r"ffn1_stack_bias$", (None, TP)),
    (r"ffn2_stack_weight$", (None, None, TP)),
], default=())

# serving KV cache: stage-major (L, B, H, W, Dh) along the scanned
# trunk — heads shard on the tp axis exactly like the qkv stacks above,
# so cached keys/values stay resident with the heads that produced them
SERVING_CACHE_AXES = (None, None, TP, None, None)


def serving_cache_sharding(mesh, tp_axis=TP):
    """NamedSharding for a (L, B, H, W, Dh) serving KV cache on ``mesh``
    (None mesh → None, the single-device path)."""
    if mesh is None:
        return None
    from jax.sharding import NamedSharding, PartitionSpec

    spec = tuple(tp_axis if a == TP else a for a in SERVING_CACHE_AXES)
    return NamedSharding(mesh, PartitionSpec(*spec))


# expert parallelism: MoE expert weights shard on their leading E axis
# (gluon/contrib/moe.py MoEFFN); the router gate stays replicated so
# every ep slice routes identically
from .mesh import EP  # noqa: E402

MOE_EP_RULES = ShardingRules(rules=[
    (r"expert_ffn\d_weight$", (EP, None, None)),
    (r"expert_ffn\d_bias$", (EP, None)),
], default=())


class _CombinedRules(ShardingRules):
    """First match wins ACROSS rule sets, shape heuristics included.

    Composable sets (`PPRules`) are the one exception: their matches
    are per-dim CLAIMS merged into the winning base spec.  A claim on a
    dim the base left None (or an absent trailing dim) fills it in; the
    same axis on the same dim is idempotent; a DIFFERENT axis on a dim
    an explicit base rule already assigned raises — silent override
    here would reshard a param two sets disagree about.  When the base
    came from the FSDP shape heuristic, the heuristic re-routes around
    claimed dims instead (it never outranks an explicit claim)."""

    def __init__(self, sets):
        super().__init__()
        self._sets = list(sets)

    def _match(self, name, shape=None):
        base = None            # (tuple spec, from_heuristic, rule set)
        claims = []            # composable (tuple spec, rule set) in order
        for rs in self._sets:
            if getattr(rs, "composable", False):
                spec = rs._match(name, shape)
                if spec is not None:
                    claims.append((tuple(spec), rs))
            elif base is None:
                if hasattr(rs, "_match_detail"):
                    spec, heur = rs._match_detail(name, shape)
                else:
                    spec, heur = rs._match(name, shape), False
                if spec is not None:
                    base = (tuple(spec), heur, rs)
        if not claims:
            if base is None:
                return None
            from jax.sharding import PartitionSpec

            return PartitionSpec(*base[0])
        return self._merge(name, shape, base, claims)

    @staticmethod
    def _merge(name, shape, base, claims):
        from jax.sharding import PartitionSpec

        ndim = len(shape) if shape else max(
            [len(s) for s, _ in claims]
            + ([len(base[0])] if base else []))
        merged = [None] * ndim
        base_spec, base_heur, base_set = base if base else ((), False,
                                                            None)
        for dim, e in enumerate(base_spec[:ndim]):
            merged[dim] = e
        claimed_dims = set()
        for spec, rs in claims:
            for dim, e in enumerate(spec[:ndim]):
                if e is None:
                    continue
                have = merged[dim]
                if have is not None and have != e:
                    if base_heur and dim not in claimed_dims:
                        merged[dim] = None  # heuristic re-routes below
                    else:
                        raise ValueError(
                            "combined_rules: conflicting axes for "
                            f"{name!r} dim {dim}: {e!r} "
                            f"(from {type(rs).__name__}) vs {have!r} — "
                            "two rule sets may not assign different "
                            "axes to the same dim of the same param")
                if e in merged and merged.index(e) != dim:
                    prev = merged.index(e)
                    if base_heur and prev not in claimed_dims:
                        # the duplicate placement came from the FSDP
                        # shape heuristic (e.g. it picked an embedding
                        # table's divisible dim 1 when the vocab dim is
                        # uneven): an explicit claim outranks it — drop
                        # it and let the end-of-merge re-route look for
                        # another dim
                        merged[prev] = None
                    else:
                        raise ValueError(
                            "combined_rules: axis {!r} claimed twice "
                            "for {!r} (dims {} and {}) — a mesh axis "
                            "shards at most one dim per param".format(
                                e, name, prev, dim))
                merged[dim] = e
                claimed_dims.add(dim)
        if base_heur and base_set is not None:
            # the heuristic's dim was taken: re-run it around the
            # claimed dims and fold in what it finds
            redo = base_set._heuristic(shape, avoid_dims=claimed_dims)
            if redo is not None:
                for dim, e in enumerate(tuple(redo)[:ndim]):
                    if e is not None and merged[dim] is None \
                            and e not in merged:
                        merged[dim] = e
        return PartitionSpec(*merged)

    def add(self, pattern, spec):
        # appended rules have the LOWEST precedence, matching the
        # concatenation semantics
        self._sets.append(ShardingRules(rules=[(pattern, spec)]))
        return self


def combined_rules(*rule_sets):
    """Merge rule sets — e.g. combined_rules(TRANSFORMER_TP_RULES,
    MOE_EP_RULES) for a tp×ep transformer, or
    combined_rules(TRANSFORMER_TP_RULES, fsdp_rules(mesh)) for TP
    weights with an FSDP fallback.

    Precedence (pinned by tests/test_parallel.py): FIRST MATCH WINS
    across the concatenation — every rule (and shape heuristic) of an
    earlier set overrides every rule of a later set on conflicting
    names, whole-spec, with no per-dim merging between ordinary sets.
    `PPRules`-style ``composable`` overlays are the exception: their
    per-dim claims merge into the winning base spec, and a conflicting
    axis on the same dim of the same param is a hard ValueError (see
    `_CombinedRules`)."""
    return _CombinedRules(rule_sets)


def match_partition_rules(rules, params):
    """Bulk resolution: ``{name: PartitionSpec}`` for every entry of
    ``params`` (a dict of name → Parameter / array / shape tuple) —
    the pytree-of-specs step between a rule set and `NamedSharding`
    placement."""
    specs = {}
    for name, p in params.items():
        shape = p if isinstance(p, (tuple, list)) \
            else getattr(p, "shape", None)
        specs[name] = rules.spec_for(name, shape)
    return specs


def annotate_block(block, rules):
    """Stamp partition_spec onto every Parameter of a block (consumed by
    ShardedTrainer when laying params over the mesh)."""
    for name, param in block.collect_params().items():
        param.partition_spec = rules.spec_for(name, param.shape)
    return block


def param_sharding(param, mesh):
    """NamedSharding for a Parameter (replicated when no spec/axis).

    Two leniencies so one rule set runs on every mesh: axes the mesh
    doesn't have drop to None, and a sharded dim whose size the axis
    does not divide drops to None too — `jax.device_put` rejects
    uneven committed placements, and an uneven-vocab embedding table
    must replicate rather than fail (`EmbeddingRules`)."""
    from jax.sharding import NamedSharding, PartitionSpec

    spec = param.partition_spec
    if spec is None:
        spec = PartitionSpec()
    # drop axes the mesh doesn't have (lets the same rules run on a
    # dp-only mesh)
    cleaned = []
    shape = getattr(param, "shape", None)
    for dim, entry in enumerate(tuple(spec)):
        if entry is None or entry in mesh.shape:
            if entry is not None and shape is not None \
                    and dim < len(shape) \
                    and shape[dim] % mesh.shape[entry] != 0:
                entry = None
            cleaned.append(entry)
        else:
            cleaned.append(None)
    return NamedSharding(mesh, PartitionSpec(*cleaned))


# -- imperative-path placement (gluon Trainer + CapturedStep) ------------------

def shard_model(block, mesh, mode="tp", rules=None, axis=DP,
                min_size=None, trainer=None):
    """Annotate AND place a gluon block's parameters over ``mesh`` —
    the imperative twin of ShardedTrainer's staging, consumed by
    `gluon.Trainer.train_step`'s captured program (gluon/captured.py).

    Modes on one rule surface:

    - ``mode='tp'``: Megatron tensor parallelism from ``rules``
      (default `TRANSFORMER_TP_RULES`) — Dense/attention weights split
      over the ``tp`` axis; pair with
      `HybridBlock.shard_activations` / `annotate_activations` for the
      activation constraints.
    - ``mode='fsdp'``: every large-enough parameter sharded over the
      data axis (`fsdp_rules`); GSPMD gathers each layer's weights
      inside the step program and reduce-scatters its gradients.
      ``rules`` (if given) overrides the shape heuristic per name.
    - ``mode='pp'``: pipeline stages only — `pp_rules(mesh)` claims the
      leading layer-stack dim of every ``*_stack_*`` param for the
      ``pp`` axis (scanned trunks: ScanTransformerEncoder / scan GPT).
    - ``mode='tp_pp'``: the pp overlay merged over TP (``rules`` or
      `TRANSFORMER_TP_RULES`) — qkv stacks land ('pp','tp',None); with
      a dp axis on the same mesh this is the full tp×pp×dp layout.
    - ``mode='pp_fsdp'``: the pp overlay over the FSDP shape heuristic;
      the heuristic re-routes around the claimed stack dim.

    Initialized parameters (and their gradient buffers) are
    `jax.device_put` onto their `NamedSharding` immediately, making
    them committed sharded arrays every later jit (CachedOp forward,
    captured step, eager grouped update) infers its layout from.
    Aux parameters (``grad_req='null'`` — BatchNorm stats) replicate.
    Also sets the process default mesh.  Returns ``{name: spec}``.

    When RE-sharding a model that already trained (an elastic gang
    reshape, or turning sharding on mid-run), pass the gluon
    ``trainer``: its existing optimizer states are committed to the
    OLD placement and must move with their weights, or the next step's
    jit sees incompatible device sets.  Fresh states (created on the
    first post-shard step) place themselves.
    """
    import jax
    from jax.sharding import PartitionSpec

    from .mesh import set_default_mesh

    # every mode carries the EmbeddingRules overlay as a SIBLING set —
    # composable claims must see the base's heuristic flag, so nesting
    # an already-combined set would lose the FSDP re-route
    emb = EmbeddingRules(axis=axis)
    user = [] if rules is None else [rules]
    if mode == "fsdp":
        sets = [emb] + user \
            + [fsdp_rules(mesh=mesh, axis=axis, min_size=min_size)]
    elif mode == "tp":
        sets = [emb, TRANSFORMER_TP_RULES] if rules is None \
            else [emb] + user
    elif mode == "pp":
        sets = [pp_rules(mesh=mesh), emb] + user
    elif mode == "tp_pp":
        sets = [pp_rules(mesh=mesh), emb,
                TRANSFORMER_TP_RULES if rules is None else rules]
    elif mode == "pp_fsdp":
        sets = [pp_rules(mesh=mesh), emb] + user \
            + [fsdp_rules(mesh=mesh, axis=axis, min_size=min_size)]
    else:
        raise ValueError(f"shard_model: unknown mode {mode!r} (expected "
                         "'tp', 'fsdp', 'pp', 'tp_pp' or 'pp_fsdp')")
    rules = combined_rules(*sets)
    from ..gluon.parameter import DeferredInitializationError

    specs = {}
    for name, p in block.collect_params().items():
        if p.grad_req == "null":
            # aux state (BN running stats) replicates in both modes
            p.partition_spec = PartitionSpec()
        else:
            p.partition_spec = rules.spec_for(name, p.shape)
        specs[name] = p.partition_spec
        sh = param_sharding(p, mesh)
        try:
            nd = p.data()
        except DeferredInitializationError:
            continue  # spec stamps now, placement at materialization
        nd._set_data(jax.device_put(nd._data, sh))
        g = getattr(p, "_grad", None)
        if g is not None and getattr(g, "_data", None) is not None \
                and getattr(p, "_grad_stype", None) != "row_sparse":
            g._set_data(jax.device_put(g._data, sh))
    if trainer is not None:
        from ..optimizer.grouped import _place_state_like

        params = list(trainer._params)
        for upd in getattr(trainer, "_updaters", []):
            for i, st in upd.states.items():
                if st is not None and 0 <= i < len(params):
                    _place_state_like(st, params[i].data())
    set_default_mesh(mesh)
    return specs


def mesh_of_params(params):
    """The Mesh an (iterable of) gluon Parameters is laid over, or None:
    the first committed multi-device `NamedSharding` found wins.  Cheap
    attribute walking only — safe on the per-step path."""
    from jax.sharding import NamedSharding

    for p in params:
        raw = getattr(getattr(p, "_data", None), "_data", None)
        sh = getattr(raw, "sharding", None)
        if isinstance(sh, NamedSharding) and sh.mesh.size > 1:
            return sh.mesh
    return None


def batch_sharding(mesh, dim_size=None, leading=0, axis=DP):
    """NamedSharding splitting the batch dimension (dim ``leading``)
    over the data axis — replicated when the mesh has no dp axis or
    ``dim_size`` is not divisible by it (uneven batches stay whole
    rather than tripping a GSPMD padding path the eager oracle would
    not take)."""
    from jax.sharding import NamedSharding, PartitionSpec

    size = mesh.shape.get(axis, 1)
    if size <= 1 or (dim_size is not None and dim_size % size != 0):
        return NamedSharding(mesh, PartitionSpec())
    return NamedSharding(mesh,
                         PartitionSpec(*([None] * leading + [axis])))


def constrain(x, mesh, spec):
    """`with_sharding_constraint` with the same leniency as
    `param_sharding`: axes absent from the mesh drop to None, and a
    spec longer than ``x``'s rank is a no-op (identity) instead of an
    error — so one activation annotation runs sharded and unsharded."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    if mesh is None or getattr(mesh, "size", 1) <= 1:
        return x
    entries = [e if e is None
               or (e in mesh.shape and mesh.shape[e] > 1) else None
               for e in tuple(spec)]
    ndim = getattr(x, "ndim", None)
    if ndim is None or len(entries) > ndim:
        return x
    # divisibility guard per sharded dim: constraint on a non-divisible
    # dim forces GSPMD padding the eager oracle never sees
    for dim, e in enumerate(entries):
        if e is not None and x.shape[dim] % mesh.shape[e] != 0:
            entries[dim] = None
    sh = NamedSharding(mesh, PartitionSpec(*entries))
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sh)
    return jax.device_put(x, sh)


def annotate_activations(block, rules, mesh=None):
    """Walk the block tree; any HybridBlock whose NAME matches a rule
    pattern gets `shard_activations(spec, mesh)` — the rules-driven way
    to place Megatron activation constraints without touching model
    code (block names, not parameter names, are matched here)."""
    def walk(b):
        if hasattr(b, "shard_activations"):
            for pat, spec in getattr(rules, "_rules", []):
                if pat.search(getattr(b, "name", "") or ""):
                    b.shard_activations(spec, mesh)
                    break
        for child in getattr(b, "_children", {}).values():
            walk(child)

    walk(block)
    return block
