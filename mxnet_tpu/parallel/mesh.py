"""Device meshes.

NEW, TPU-first (SURVEY.md §2.5/§2.6): the reference scales by replicating
per-GPU handles + NCCL/PS reduction; here multi-chip scale is a
``jax.sharding.Mesh`` with named axes and everything else is a sharding
annotation.  Axis-name conventions used across the framework:

- ``dp``: data parallel (batch dim)
- ``tp``: tensor parallel (Megatron-style weight sharding)
- ``pp``: pipeline stages
- ``sp``: sequence/context parallel (ring attention)
- ``ep``: expert parallel (MoE)
"""

from __future__ import annotations

import numpy as _np

DP, TP, PP, SP, EP = "dp", "tp", "pp", "sp", "ep"


def make_mesh(dp=1, tp=1, pp=1, sp=1, ep=1, devices=None, axes=None):
    """Build a Mesh with the canonical axis order (pp, dp, sp, ep, tp).

    tp innermost: it carries the most latency-sensitive collectives, and the
    innermost mesh dim maps to physically-adjacent chips on the ICI torus
    (the scaling-book layout recipe).  pp outermost: stage transfers are
    point-to-point and tolerate DCN.

    ``axes={"tp": 2, "pp": 2, "dp": 2}`` is the dict form for 3-axis
    layouts — equivalent to the keyword form, same canonical order, same
    per-axis validation; unknown axis names raise.  Mixing ``axes`` with
    a non-default keyword size is ambiguous and raises.
    """
    import jax
    from jax.sharding import Mesh

    override = devices is not None
    if devices is None:
        devices = jax.devices()
    if axes is not None:
        kw = {"pp": pp, "dp": dp, "sp": sp, "ep": ep, "tp": tp}
        clash = [n for n, s in kw.items() if s != 1]
        if clash:
            raise ValueError(
                "make_mesh: pass axis sizes either as keywords or via "
                f"axes=, not both (keyword {clash[0]}={kw[clash[0]]!r} "
                f"alongside axes={axes!r})")
        unknown = [n for n in axes if n not in kw]
        if unknown:
            raise ValueError(
                f"make_mesh: unknown axis {unknown[0]!r} in axes= "
                f"(expected a subset of {sorted(kw)})")
        pp = axes.get("pp", 1)
        dp = axes.get("dp", 1)
        sp = axes.get("sp", 1)
        ep = axes.get("ep", 1)
        tp = axes.get("tp", 1)
    sizes = {"pp": pp, "dp": dp, "sp": sp, "ep": ep, "tp": tp}
    for name, size in sizes.items():
        if not isinstance(size, int) or size < 1:
            raise ValueError(
                f"make_mesh: axis {name}={size!r} must be a positive int")
    axes = [(name, size) for name, size in sizes.items() if size > 1]
    if not axes:
        axes = [("dp", 1)]
    total = 1
    for _, s in sizes.items():
        total *= s
    if total > len(devices):
        # clear, early ValueError naming the axis product and the device
        # count — not whatever jax raises downstream from a bad reshape
        product = " * ".join(f"{n}={s}" for n, s in sizes.items()
                             if s > 1) or "dp=1"
        source = "devices= override" if override else "jax.devices()"
        raise ValueError(
            f"make_mesh: axis product {product} = {total} devices, but "
            f"only {len(devices)} available from {source}")
    names = [n for n, _ in axes]
    shape = [s for _, s in axes]
    arr = _np.asarray(devices[:total]).reshape(shape)
    return Mesh(arr, names)


def data_parallel_mesh(n=None):
    import jax

    n = n or len(jax.devices())
    return make_mesh(dp=n)


def mesh_axis_size(mesh, name):
    return mesh.shape.get(name, 1)


_DEFAULT_MESH = None


def set_default_mesh(mesh):
    """Set the process-wide default mesh (consumed by ring attention and
    other mesh-aware ops when no mesh is passed explicitly)."""
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh
    return mesh


def default_mesh():
    return _DEFAULT_MESH


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def sharded(mesh, *spec):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec))
