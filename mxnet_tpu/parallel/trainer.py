"""Whole-step-compiled sharded training.

TPU-first centerpiece (SURVEY.md §7): where the reference runs
forward → backward → kvstore-reduce → optimizer as separate engine pushes
(gluon/trainer.py + src/kvstore/), ``ShardedTrainer`` compiles the ENTIRE
training step — forward, backward, gradient reduction, optimizer update,
BatchNorm aux updates — into ONE XLA program over a device mesh:

- the batch is a single global array sharded on the ``dp`` axis;
- parameters carry PartitionSpecs (sharding.py TP rules) and GSPMD inserts
  all collectives (dp grad psum, Megatron tp all-reduces) over ICI;
- optimizer state shards exactly like its parameter;
- input/param/opt buffers are donated — no per-step reallocation.

This is simultaneously the analog of CachedOp bulked execution, kvstore
all-reduce, and the fused optimizer ops, in one compiled artifact.
"""

from __future__ import annotations

import math

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _from_jax
from ..ops import optimizer_op as _op
from .mesh import DP, data_parallel_mesh
from .sharding import (ShardingRules, annotate_block, fsdp_rules,
                       param_sharding)


class _PureOptimizer:
    """Pure-functional optimizer over a list of param arrays.

    Mirrors the stateful mxnet_tpu.optimizer registry; state is a pytree
    sharded like its parameters.
    """

    def __init__(self, name, lr=0.01, momentum=0.0, wd=0.0, beta1=0.9,
                 beta2=0.999, epsilon=1e-8, clip_gradient=None,
                 lr_scheduler=None, gamma1=None, rho=None, gamma2=0.9,
                 centered=False, lower_bound=None, upper_bound=None,
                 clip_weights=None, lazy_update=True, **unknown):
        self.name = name.lower()
        self.lr = lr
        self.momentum = momentum
        self.wd = wd
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.clip_gradient = clip_gradient
        self.lr_scheduler = lr_scheduler
        # rmsprop decay: reference calls it gamma1, torch-style calls rho
        self.gamma1 = gamma1 if gamma1 is not None else \
            (rho if rho is not None else 0.9)
        self.gamma2 = gamma2
        self.centered = bool(centered)
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.clip_weights = clip_weights
        if unknown:
            # reference-compatible knobs with no effect here (grads are
            # mean-normalized inside the compiled step; compute dtype is
            # set by block.cast) — warn, don't crash ported scripts
            import warnings

            warnings.warn(
                f"ShardedTrainer: ignoring optimizer hyperparameters "
                f"{sorted(unknown)} for {name}", stacklevel=3)
        if self.name not in ("sgd", "nag", "adam", "adamw", "lamb",
                             "rmsprop", "adagrad"):
            raise MXNetError(f"ShardedTrainer: unsupported optimizer "
                             f"{name}")

    def lr_at(self, num_update):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(num_update)
        return self.lr

    def n_states(self):
        if self.name == "rmsprop":
            return 3 if self.centered else 1
        return {"sgd": 1, "nag": 1, "adagrad": 1,
                "adam": 2, "adamw": 2, "lamb": 2}[self.name]

    def init_state(self, param_vals):
        import jax.numpy as jnp

        n = self.n_states()
        return [tuple(jnp.zeros_like(p) for _ in range(n))
                for p in param_vals]

    def apply(self, param_vals, grads, states, lr, t, wd_mults, lr_mults,
              rescale):
        """One pure update over all params; returns (new_params,
        new_states)."""
        import jax.numpy as jnp

        kw = {"rescale_grad": rescale}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        new_p, new_s = [], []
        for p, g, s, wm, lm in zip(param_vals, grads, states, wd_mults,
                                   lr_mults):
            wd = self.wd * wm
            plr = lr * lm
            if self.name == "sgd":
                if self.momentum:
                    w, mom = _op.sgd_mom_update_pure(
                        p, g, s[0], lr=plr, momentum=self.momentum, wd=wd,
                        **kw)
                    s_out = (mom,)
                else:
                    (w,) = _op.sgd_update_pure(p, g, lr=plr, wd=wd, **kw)
                    s_out = s
            elif self.name == "nag":
                w, mom = _op.nag_mom_update_pure(
                    p, g, s[0], lr=plr, momentum=self.momentum, wd=wd, **kw)
                s_out = (mom,)
            elif self.name in ("adam", "adamw"):
                coef1 = 1.0 - self.beta1 ** t
                coef2 = 1.0 - self.beta2 ** t
                lr_t = plr * jnp.sqrt(coef2) / coef1
                fn = _op.adam_update_pure if self.name == "adam" else \
                    _op.adamw_update_pure
                w, m, v = fn(p, g, s[0], s[1], lr=lr_t, beta1=self.beta1,
                             beta2=self.beta2, epsilon=self.epsilon, wd=wd,
                             **kw)
                s_out = (m, v)
            elif self.name == "lamb":
                gnew, m, v = _op.lamb_update_phase1_pure(
                    p, g, s[0], s[1], t=t, beta1=self.beta1,
                    beta2=self.beta2, epsilon=self.epsilon, wd=wd, **kw)
                r1 = jnp.linalg.norm(p)
                r2 = jnp.linalg.norm(gnew)
                bounds = {}
                if self.lower_bound is not None:
                    bounds["lower_bound"] = self.lower_bound
                if self.upper_bound is not None:
                    bounds["upper_bound"] = self.upper_bound
                (w,) = _op.lamb_update_phase2_pure(p, gnew, r1, r2, lr=plr,
                                                   **bounds)
                s_out = (m, v)
            elif self.name == "rmsprop":
                cw = {"clip_weights": self.clip_weights} \
                    if self.clip_weights is not None else {}
                if self.centered:
                    w, n, gm, d = _op.rmspropalex_update_pure(
                        p, g, s[0], s[1], s[2], lr=plr, gamma1=self.gamma1,
                        gamma2=self.gamma2, epsilon=self.epsilon, wd=wd,
                        **kw, **cw)
                    s_out = (n, gm, d)
                else:
                    w, n = _op.rmsprop_update_pure(
                        p, g, s[0], lr=plr, gamma1=self.gamma1,
                        epsilon=self.epsilon, wd=wd, **kw, **cw)
                    s_out = (n,)
            elif self.name == "adagrad":
                w, h = _op.adagrad_update_pure(
                    p, g, s[0], lr=plr, epsilon=self.epsilon, wd=wd, **kw)
                s_out = (h,)
            # the f32 lr scalar promotes the update math to f32 — cast
            # back so bf16 weights stay bf16 across steps (the reference
            # updaters preserve weight dtype; dtype drift would also
            # retrace the jitted step every call)
            w = w.astype(p.dtype)
            s_out = tuple(s_new.astype(s_old.dtype)
                          for s_new, s_old in zip(s_out, s))
            new_p.append(w)
            new_s.append(s_out)
        return new_p, new_s


class ShardedTrainer:
    """Train a gluon Block with one compiled step over a Mesh.

    Usage::

        mesh = parallel.make_mesh(dp=4, tp=2)
        trainer = parallel.ShardedTrainer(net, loss_fn, 'adam',
                                          {'learning_rate': 1e-3},
                                          mesh=mesh,
                                          rules=parallel.TRANSFORMER_TP_RULES)
        loss = trainer.step(x, y)   # one XLA program per step
    """

    def __init__(self, block, loss_fn, optimizer="sgd",
                 optimizer_params=None, mesh=None, rules=None,
                 batch_axis=DP, grad_accum=1, remat=None, mode=None):
        import jax

        from .. import engine
        engine.ensure_compile_cache()  # MXTPU_COMPILE_CACHE_DIR, if set
        self.block = block
        self.loss_fn = loss_fn
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self.batch_axis = batch_axis
        self.remat = remat
        opt_kwargs = dict(optimizer_params or {})
        lr = opt_kwargs.pop("learning_rate", opt_kwargs.pop("lr", 0.01))
        self.optimizer = _PureOptimizer(optimizer, lr=lr, **opt_kwargs)
        if mode == "fsdp" and rules is None:
            # FSDP over the batch axis: rules resolve per-shape, so
            # annotation is deferred to _stage (after deferred init)
            rules = fsdp_rules(mesh=self.mesh, axis=batch_axis)
        elif mode not in (None, "tp", "fsdp"):
            raise MXNetError(f"ShardedTrainer: unknown mode {mode!r} "
                             "(expected 'tp' or 'fsdp')")
        self._rules = rules
        if rules is not None:
            annotate_block(block, rules)
        self._grad_accum = int(grad_accum)
        assert self._grad_accum >= 1
        self._num_update = 0
        self._step_fn = None
        self._initialized = False

    # -- parameter staging -----------------------------------------------------

    def _stage(self, example):
        """Collect params (after deferred init), lay them on the mesh."""
        import jax

        # materialize deferred shapes with one throwaway eager pass
        from .. import autograd as _ag
        from ..gluon.block import _TRACE

        needs = any(p._deferred_init
                    for p in self.block.collect_params().values())
        if needs:
            prev = _TRACE.force_eager
            _TRACE.force_eager = True
            try:
                with _ag.pause():
                    self.block(example)
            finally:
                _TRACE.force_eager = prev
        if self._rules is not None:
            # re-resolve with materialized shapes: shape-driven rules
            # (FSDPRules) see None for deferred params at __init__ time
            annotate_block(self.block, self._rules)
        allp = list(self.block.collect_params().items())
        self._trainable = [(n, p) for n, p in allp if p.grad_req != "null"]
        self._aux = [(n, p) for n, p in allp if p.grad_req == "null"]
        self._param_shardings = [param_sharding(p, self.mesh)
                                 for _, p in self._trainable]
        self._param_vals = [
            jax.device_put(p.data()._data, s)
            for (_, p), s in zip(self._trainable, self._param_shardings)]
        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(self.mesh, PartitionSpec())
        self._aux_vals = {n: jax.device_put(p.data()._data, repl)
                          for n, p in self._aux}
        self._opt_state = self.optimizer.init_state(self._param_vals)
        self._opt_state = [
            tuple(jax.device_put(s, sh) for s in states)
            for states, sh in zip(self._opt_state, self._param_shardings)]
        self._wd_mults = [p.wd_mult for _, p in self._trainable]
        self._lr_mults = [p.lr_mult for _, p in self._trainable]
        self._initialized = True

    def _build_step(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        from .. import autograd as _ag
        from .. import random as _random
        from ..gluon.block import _TRACE

        block = self.block
        loss_block = self.loss_fn
        optimizer = self.optimizer
        t_ids = [id(p) for _, p in self._trainable]
        a_names = [n for n, _ in self._aux]
        a_ids = [id(p) for _, p in self._aux]
        wd_mults = tuple(self._wd_mults)
        lr_mults = tuple(self._lr_mults)

        grad_accum = self._grad_accum

        def pure_step(param_vals, opt_state, aux_vals, x, y, key, lr, t):
            def loss_of(pv, aux_cur, xb, yb, kb):
                from ..gluon.block import param_override_scope

                pm = dict(zip(t_ids, pv))
                pm.update({i: aux_cur[n]
                           for i, n in zip(a_ids, a_names)})
                aux_upd = {}
                with param_override_scope(pm, aux_upd), \
                        _random.key_scope(kb), _ag.train_mode():
                    out = block.forward(xb)
                    loss = loss_block(out, yb) \
                        if loss_block is not None else out
                return jnp.mean(loss), aux_upd

            # remat='full'|'dots'|... or MXNET_BACKWARD_DO_MIRROR: the
            # backward recomputes activations (reference mirror pass)
            from .. import remat as _remat

            loss_of = _remat.wrap(loss_of, self.remat)

            if grad_accum == 1:
                (loss, aux_upd), grads = jax.value_and_grad(
                    loss_of, has_aux=True)(param_vals, aux_vals, x, y, key)
                new_aux = dict(aux_vals)
                new_aux.update(aux_upd)
            else:
                # microbatch the leading dim; one optimizer update from
                # the averaged gradients (reference grad_req='add' +
                # delayed trainer.step semantics, compiled).  Aux (BN
                # running stats) threads through the scan carry so each
                # microbatch applies its momentum update to the stats the
                # previous microbatch produced — k sequential updates per
                # step, matching the reference's k forward passes.
                def reshape(a):
                    return a.reshape((grad_accum, -1) + a.shape[1:])

                xm = jax.tree_util.tree_map(reshape, x)
                ym = jax.tree_util.tree_map(reshape, y)
                keys = jax.random.split(key, grad_accum)

                def body(carry, micro):
                    l_acc, g_acc, aux_cur = carry
                    xb, yb, kb = micro
                    (l, aux_upd), g = jax.value_and_grad(
                        loss_of, has_aux=True)(param_vals, aux_cur, xb,
                                               yb, kb)
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                    aux_next = dict(aux_cur)
                    aux_next.update(aux_upd)
                    return (l_acc + l, g_acc, aux_next), None

                g0 = jax.tree_util.tree_map(jnp.zeros_like, param_vals)
                (l_tot, g_tot, new_aux), _ = jax.lax.scan(
                    body, (0.0, g0, dict(aux_vals)), (xm, ym, keys))
                loss = l_tot / grad_accum
                grads = jax.tree_util.tree_map(
                    lambda g: g / grad_accum, g_tot)
            # loss_of returns the MEAN loss → grads are already
            # batch-normalized; rescale_grad stays 1 (the reference's
            # rescale=1/batch applies to summed grads)
            new_p, new_s = optimizer.apply(
                param_vals, grads, opt_state, lr, t, wd_mults, lr_mults,
                1.0)
            return new_p, new_s, new_aux, loss

        repl = NamedSharding(self.mesh, PartitionSpec())
        batch_spec = NamedSharding(self.mesh,
                                   PartitionSpec(self.batch_axis))
        self._batch_sharding = batch_spec
        in_shardings = (
            self._param_shardings,
            [tuple(sh for _ in states) for states, sh in
             zip(self._opt_state, self._param_shardings)],
            {n: repl for n, _ in self._aux},
            batch_spec, batch_spec, repl, None, None)
        out_shardings = (
            self._param_shardings,
            [tuple(sh for _ in states) for states, sh in
             zip(self._opt_state, self._param_shardings)],
            {n: repl for n, _ in self._aux},
            repl)
        with self.mesh:
            self._step_fn = jax.jit(
                pure_step,
                in_shardings=in_shardings,
                out_shardings=out_shardings,
                donate_argnums=(0, 1, 2))

    # -- public API ------------------------------------------------------------

    def step(self, data, label):
        """Run ONE compiled train step; returns the (replicated) loss.
        `data`/`label` may be arrays or pytrees of arrays (e.g. BERT's
        (mlm_labels, nsp_labels) tuple), batch-major on dim 0.  With
        grad_accum=k the batch is split into k microbatches inside the
        compiled step."""
        import jax
        import jax.numpy as jnp
        import jax.tree_util as jtu

        from .. import random as _random

        def to_raw(v):
            return v._data if isinstance(v, NDArray) else jnp.asarray(v)

        x = jtu.tree_map(to_raw, data)
        y = jtu.tree_map(to_raw, label)
        if not self._initialized:
            self._stage(jtu.tree_map(_from_jax, x))
            # autotune DB consult at capture time (replay-only on the
            # sharded path): a stored winner's knobs (bucket MB, FSDP
            # min size, remat, ...) must be in env BEFORE the step
            # program is traced
            from .. import autotune as _autotune

            _autotune.replay_for_sharded(
                _autotune.sharded_signature(self, x), self.mesh)
            self._build_step()
        x = jax.device_put(x, self._batch_sharding)
        y = jax.device_put(y, self._batch_sharding)
        self._num_update += 1
        t = self._num_update
        lr = self.optimizer.lr_at(t)
        key = _random.next_key()
        # MXTPU_STEP_TIMEOUT arms a watchdog around the dispatch: a step
        # wedged inside the runtime (dead tunnel, stuck collective) dumps
        # thread stacks and errors out instead of hanging the driver
        from .. import resilience

        with resilience.guard_step(f"train_step {t}"):
            self._param_vals, self._opt_state, self._aux_vals, loss = \
                self._step_fn(self._param_vals, self._opt_state,
                              self._aux_vals, x, y, key,
                              jnp.asarray(lr, jnp.float32),
                              jnp.asarray(t, jnp.float32))
        return _from_jax(loss)

    def state_dict(self):
        """Full train state as a pytree (params + optimizer + step) for
        checkpointing; valid after the first step (or _stage).  The
        resilience.run_resilient get_state hook for sharded training."""
        from .. import checkpoint

        return checkpoint.trainer_state(self)

    def load_state_dict(self, state):
        """Load a state_dict()/checkpoint pytree back onto the mesh (the
        run_resilient set_state hook)."""
        from .. import checkpoint

        checkpoint.load_trainer_state(self, state)

    def state_template(self):
        """Elastic-restore template: `state_dict()`'s structure with this
        trainer's shardings at every array position.  Pass it to
        ``checkpoint.AsyncCheckpointer.restore(step, template=...)`` to
        re-lay a checkpoint written under a different world size or mesh
        onto this trainer's layout."""
        from .. import checkpoint

        return checkpoint.trainer_state_template(self)

    def reshape_mesh(self, mesh=None):
        """Re-lay this trainer onto a new mesh (the elastic N→M reshape,
        `resilience.ElasticGang`).

        After a gang membership change the device topology the step
        program compiled against is gone; this snapshots the full train
        state to host, rebuilds the mesh (default: a fresh
        data-parallel mesh over the CURRENT device set), recomputes the
        shardings, re-places every buffer, and recompiles the step —
        state values are preserved exactly, so the post-reshape loss
        trajectory matches a fresh trainer restored from the same
        snapshot."""
        if not self._initialized:
            self.mesh = mesh if mesh is not None else data_parallel_mesh()
            return self
        from .. import checkpoint

        state = checkpoint.trainer_state(self)
        self.mesh = mesh if mesh is not None else data_parallel_mesh()
        self._param_shardings = [param_sharding(p, self.mesh)
                                 for _, p in self._trainable]
        checkpoint.load_trainer_state(self, state)
        self._step_fn = None
        self._build_step()
        return self

    def sync_params(self):
        """Write the mesh-resident values back into the gluon Parameters
        (handle swap, no host transfer)."""
        for (name, p), val in zip(self._trainable, self._param_vals):
            p.data()._set_data(val)
        for name, p in self._aux:
            p.data()._set_data(self._aux_vals[name])

    @property
    def learning_rate(self):
        return self.optimizer.lr_at(self._num_update)

    def set_learning_rate(self, lr):
        self.optimizer.lr = lr
        self.optimizer.lr_scheduler = None


# DataParallelTrainer: the common case — pure DP mesh, no TP rules
class DataParallelTrainer(ShardedTrainer):
    def __init__(self, block, loss_fn, optimizer="sgd",
                 optimizer_params=None, n_devices=None):
        super().__init__(block, loss_fn, optimizer, optimizer_params,
                         mesh=data_parallel_mesh(n_devices))
