"""Multi-host process bootstrap.

Reference parity: ps-lite's Postoffice/Van rendezvous + the dmlc tracker
env contract (DMLC_ROLE/DMLC_PS_ROOT_URI/DMLC_NUM_WORKER, SURVEY.md §5.6
plane 4).

TPU-native: one coordinator rendezvous via ``jax.distributed.initialize``;
the env contract is MXTPU_COORDINATOR / MXTPU_NUM_WORKERS /
MXTPU_WORKER_RANK, set by tools/launch.py.  After init, every process sees
the global device set and collectives span hosts over ICI/DCN
automatically.  Checkpoint-restart is the recovery primitive (SURVEY.md
§5.3: elasticity is out of scope, matching the reference).

Robustness (resilience.py): a coordinator that is slow to come up — the
normal case when a relaunched gang races its rank-0 — is retried with
exponential backoff under ``MXTPU_RENDEZVOUS_RETRIES`` attempts /
``MXTPU_RENDEZVOUS_TIMEOUT`` seconds total; ``distributed.barrier`` arms
a watchdog from ``MXTPU_COLLECTIVE_TIMEOUT`` so a dead peer produces a
stack dump and a clean error instead of an infinite hang.
"""

from __future__ import annotations

import os

from . import resilience

_INITIALIZED = False


def _rendezvous(coordinator_address, num_processes, process_id):
    """One retried rendezvous attempt loop (coordinator-unreachable is
    the retryable class; the MXTPU_FAULT_INJECT 'rendezvous' site tests
    it hermetically)."""
    import jax

    timeout = float(os.environ.get("MXTPU_RENDEZVOUS_TIMEOUT", 300))
    retries = int(os.environ.get("MXTPU_RENDEZVOUS_RETRIES", 3))

    def attempt():
        resilience.inject_failure("rendezvous")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)

    resilience.retry_call(
        attempt, retries=retries, deadline=timeout, backoff=0.5,
        max_backoff=10.0,
        retryable=(RuntimeError, ConnectionError, OSError,
                   resilience.InjectedFault),
        description=f"rendezvous with {coordinator_address}")


def init_from_env():
    """Join the rendezvous if launch env vars are present; no-op
    otherwise.  Returns True if running distributed."""
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coord = os.environ.get("MXTPU_COORDINATOR")
    if not coord:
        return False
    _rendezvous(coord, int(os.environ["MXTPU_NUM_WORKERS"]),
                int(os.environ["MXTPU_WORKER_RANK"]))
    _INITIALIZED = True
    return True


def initialize(coordinator_address=None, num_processes=None,
               process_id=None):
    """Explicit init (reference analog: ps::Postoffice::Start)."""
    global _INITIALIZED
    _rendezvous(coordinator_address, num_processes, process_id)
    _INITIALIZED = True


def rank():
    import jax

    return jax.process_index()


def num_workers():
    import jax

    return jax.process_count()


_BARRIER_N = 0


def _coordination_client():
    """The process's coordination-service client, or None when not
    running distributed (single process / uninitialized)."""
    try:
        from jax._src import distributed as _jdist

        return _jdist.global_state.client
    except Exception:
        return None


def barrier(name="mxtpu_barrier"):
    """Block until every process reaches this barrier.

    Multi-process: uses the coordination-service barrier (gRPC via the
    rendezvous coordinator) — backend-agnostic, so it works where XLA
    cross-process collectives don't exist (the CPU backend used by the
    hermetic 2-process tests).  Single-process: sync_global_devices,
    which also drains in-flight device work.

    Guarded by MXTPU_COLLECTIVE_TIMEOUT: a dead peer produces a stack
    dump and a clean error/abort instead of an infinite hang; the
    barrier's own RPC deadline (2x the watchdog, 1800s unguarded) is the
    defense-in-depth behind it.

    This is also the sync point of checkpoint.AsyncCheckpointer's
    two-phase commit ("ckpt_shards_<step>" — every shard durable before
    rank 0 renames the manifest — and "ckpt_commit_<step>"), so a rank
    that dies mid-checkpoint surfaces here, as a watchdog abort, rather
    than as a torn checkpoint.
    """
    global _BARRIER_N
    with resilience.guard_collective(f"barrier:{name}"):
        client = _coordination_client()
        if client is not None:
            _BARRIER_N += 1
            timeout = float(
                os.environ.get("MXTPU_COLLECTIVE_TIMEOUT") or 900)
            client.wait_at_barrier(f"mxtpu:{name}#{_BARRIER_N}",
                                   timeout_in_ms=int(timeout * 2000))
        else:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)
