"""Multi-host process bootstrap.

Reference parity: ps-lite's Postoffice/Van rendezvous + the dmlc tracker
env contract (DMLC_ROLE/DMLC_PS_ROOT_URI/DMLC_NUM_WORKER, SURVEY.md §5.6
plane 4).

TPU-native: one coordinator rendezvous via ``jax.distributed.initialize``;
the env contract is MXTPU_COORDINATOR / MXTPU_NUM_WORKERS /
MXTPU_WORKER_RANK, set by tools/launch.py.  After init, every process sees
the global device set and collectives span hosts over ICI/DCN
automatically.  Checkpoint-restart is the recovery primitive (SURVEY.md
§5.3: elasticity is out of scope, matching the reference).

Robustness (resilience.py): a coordinator that is slow to come up — the
normal case when a relaunched gang races its rank-0 — is retried with
exponential backoff under ``MXTPU_RENDEZVOUS_RETRIES`` attempts /
``MXTPU_RENDEZVOUS_TIMEOUT`` seconds total; ``distributed.barrier`` arms
a watchdog from ``MXTPU_COLLECTIVE_TIMEOUT`` so a dead peer produces a
stack dump and a clean error instead of an infinite hang.

Elastic gang plane (PR 8): a small key-value control plane the health
plane (`resilience.HeartbeatPublisher` / `FailureDetector`) and the
membership protocol (`resilience.ElasticGang`) publish through.  Two
transports behind one ``put/get/scan/delete`` surface:

- :class:`FileKV` — a shared directory (``MXTPU_GANG_DIR``), atomic
  rename writes.  Survives any member's death, needs no coordinator,
  and is what the hermetic single-host gangs (tools/launch.py local
  launcher, the multi-process tests) use.
- :class:`CoordKV` — the jax coordination-service key-value store (the
  same gRPC plane `barrier` uses), for real multi-host pods.

`gang_kv()` picks the transport.
"""

from __future__ import annotations

import json
import os

from . import resilience

_INITIALIZED = False


def _rendezvous(coordinator_address, num_processes, process_id):
    """One retried rendezvous attempt loop (coordinator-unreachable is
    the retryable class; the MXTPU_FAULT_INJECT 'rendezvous' site tests
    it hermetically)."""
    import jax

    timeout = float(os.environ.get("MXTPU_RENDEZVOUS_TIMEOUT", 300))
    retries = int(os.environ.get("MXTPU_RENDEZVOUS_RETRIES", 3))

    def attempt():
        resilience.inject_failure("rendezvous")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)

    resilience.retry_call(
        attempt, retries=retries, deadline=timeout, backoff=0.5,
        max_backoff=10.0,
        retryable=(RuntimeError, ConnectionError, OSError,
                   resilience.InjectedFault),
        description=f"rendezvous with {coordinator_address}")


def init_from_env():
    """Join the rendezvous if launch env vars are present; no-op
    otherwise.  Returns True if running distributed."""
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coord = os.environ.get("MXTPU_COORDINATOR")
    if not coord:
        return False
    _rendezvous(coord, int(os.environ["MXTPU_NUM_WORKERS"]),
                int(os.environ["MXTPU_WORKER_RANK"]))
    _INITIALIZED = True
    return True


def initialize(coordinator_address=None, num_processes=None,
               process_id=None):
    """Explicit init (reference analog: ps::Postoffice::Start)."""
    global _INITIALIZED
    _rendezvous(coordinator_address, num_processes, process_id)
    _INITIALIZED = True


def rank():
    import jax

    return jax.process_index()


def num_workers():
    import jax

    return jax.process_count()


_BARRIER_N = 0


def _coordination_client():
    """The process's coordination-service client, or None when not
    running distributed (single process / uninitialized)."""
    try:
        from jax._src import distributed as _jdist

        return _jdist.global_state.client
    except Exception:
        return None


def barrier(name="mxtpu_barrier"):
    """Block until every process reaches this barrier.

    Multi-process: uses the coordination-service barrier (gRPC via the
    rendezvous coordinator) — backend-agnostic, so it works where XLA
    cross-process collectives don't exist (the CPU backend used by the
    hermetic 2-process tests).  Single-process: sync_global_devices,
    which also drains in-flight device work.

    Guarded by MXTPU_COLLECTIVE_TIMEOUT: a dead peer produces a stack
    dump and a clean error/abort instead of an infinite hang; the
    barrier's own RPC deadline (2x the watchdog, 1800s unguarded) is the
    defense-in-depth behind it.

    This is also the sync point of checkpoint.AsyncCheckpointer's
    two-phase commit ("ckpt_shards_<step>" — every shard durable before
    rank 0 renames the manifest — and "ckpt_commit_<step>"), so a rank
    that dies mid-checkpoint surfaces here, as a watchdog abort, rather
    than as a torn checkpoint.
    """
    global _BARRIER_N
    with resilience.guard_collective(f"barrier:{name}"):
        client = _coordination_client()
        if client is not None:
            _BARRIER_N += 1
            timeout = float(
                os.environ.get("MXTPU_COLLECTIVE_TIMEOUT") or 900)
            client.wait_at_barrier(f"mxtpu:{name}#{_BARRIER_N}",
                                   timeout_in_ms=int(timeout * 2000))
        else:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)


# ---------------------------------------------------------------------------
# Elastic gang control plane (PR 8).
#
# The health plane must keep working *while a member is dead*, which the
# coordination-service barrier above cannot do (wait_at_barrier blocks on
# the dead peer).  So membership state lives in a plain KV store with no
# fate-sharing: writes are per-rank, reads never block on a peer.


class FileKV:
    """Shared-directory key-value store with atomic rename writes.

    Keys are slash-separated paths (``hb/0``, ``epoch/current``,
    ``epoch_ack/3/1``); values are bytes.  A write is tmp-file + rename,
    so readers see either the old or the new value, never a torn one.
    No locks, no daemons: any member (or an outside supervisor like
    ``tools/launch.py --elastic``) can read the gang's state at any
    time, including after every member is dead.
    """

    def __init__(self, root):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key):
        if ".." in key.split("/"):
            raise ValueError(f"bad kv key: {key!r}")
        return os.path.join(self.root, *key.split("/"))

    def put(self, key, value):
        if isinstance(value, str):
            value = value.encode("utf-8")
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(value)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get(self, key, default=None):
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except (FileNotFoundError, NotADirectoryError):
            return default

    def scan(self, prefix):
        """All (key, value) pairs under ``prefix`` (non-recursive)."""
        base = self._path(prefix)
        try:
            names = sorted(os.listdir(base))
        except (FileNotFoundError, NotADirectoryError):
            return []
        out = []
        for name in names:
            if name.startswith(".") or ".tmp." in name:
                continue
            full = os.path.join(base, name)
            if not os.path.isfile(full):
                continue
            try:
                with open(full, "rb") as f:
                    out.append((f"{prefix}/{name}", f.read()))
            except FileNotFoundError:
                continue
        return out

    def delete(self, key):
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    # JSON convenience layer — everything the gang publishes is JSON.
    def put_json(self, key, obj):
        self.put(key, json.dumps(obj, sort_keys=True))

    def get_json(self, key, default=None):
        raw = self.get(key)
        if raw is None:
            return default
        try:
            return json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return default


class CoordKV:
    """KV plane over the jax coordination service (multi-host pods).

    Best-effort: the coordination service dies with rank 0's process, so
    this transport only covers failures of non-coordinator ranks.  Real
    deployments that need full coverage point MXTPU_GANG_DIR at a shared
    filesystem (or future: an external store) instead.
    """

    def __init__(self, client):
        self._client = client

    def put(self, key, value):
        if isinstance(value, bytes):
            value = value.decode("utf-8", "replace")
        self._client.key_value_set(f"mxtpu_gang/{key}", value,
                                   allow_overwrite=True)

    def get(self, key, default=None):
        getter = getattr(self._client, "key_value_try_get", None)
        if getter is None:
            return default
        try:
            return getter(f"mxtpu_gang/{key}").encode("utf-8")
        except Exception:
            return default

    def scan(self, prefix):
        getter = getattr(self._client, "key_value_dir_get", None)
        if getter is None:
            return []
        try:
            pairs = getter(f"mxtpu_gang/{prefix}/")
        except Exception:
            return []
        out = []
        for key, value in pairs:
            if key.startswith("mxtpu_gang/"):
                key = key[len("mxtpu_gang/"):]
            out.append((key.rstrip("/"), value.encode("utf-8")))
        return out

    def delete(self, key):
        try:
            self._client.key_value_delete(f"mxtpu_gang/{key}")
        except Exception:
            pass

    def put_json(self, key, obj):
        self.put(key, json.dumps(obj, sort_keys=True))

    def get_json(self, key, default=None):
        raw = self.get(key)
        if raw is None:
            return default
        try:
            return json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return default


def gang_kv():
    """The elastic control plane's KV transport, or None when elastic
    recovery has nowhere to publish (no gang dir, not distributed)."""
    root = os.environ.get("MXTPU_GANG_DIR")
    if root:
        return FileKV(root)
    client = _coordination_client()
    if client is not None and hasattr(client, "key_value_set"):
        return CoordKV(client)
    return None
