"""Multi-host process bootstrap.

Reference parity: ps-lite's Postoffice/Van rendezvous + the dmlc tracker
env contract (DMLC_ROLE/DMLC_PS_ROOT_URI/DMLC_NUM_WORKER, SURVEY.md §5.6
plane 4).

TPU-native: one coordinator rendezvous via ``jax.distributed.initialize``;
the env contract is MXTPU_COORDINATOR / MXTPU_NUM_WORKERS /
MXTPU_WORKER_RANK, set by tools/launch.py.  After init, every process sees
the global device set and collectives span hosts over ICI/DCN
automatically.  Checkpoint-restart is the recovery primitive (SURVEY.md
§5.3: elasticity is out of scope, matching the reference).

Robustness (resilience.py): a coordinator that is slow to come up — the
normal case when a relaunched gang races its rank-0 — is retried with
exponential backoff under ``MXTPU_RENDEZVOUS_RETRIES`` attempts /
``MXTPU_RENDEZVOUS_TIMEOUT`` seconds total; ``distributed.barrier`` arms
a watchdog from ``MXTPU_COLLECTIVE_TIMEOUT`` so a dead peer produces a
stack dump and a clean error instead of an infinite hang.

Elastic gang plane (PR 8): a small key-value control plane the health
plane (`resilience.HeartbeatPublisher` / `FailureDetector`) and the
membership protocol (`resilience.ElasticGang`) publish through.  Two
transports behind one ``put/get/scan/delete`` surface:

- :class:`FileKV` — a shared directory (``MXTPU_GANG_DIR``), atomic
  rename writes.  Survives any member's death, needs no coordinator,
  and is what the hermetic single-host gangs (tools/launch.py local
  launcher, the multi-process tests) use.
- :class:`TcpKV` — a real network transport (PR 12): length-prefixed
  CRC'd frames to a small stdlib-only daemon (:class:`GangKVServer`,
  embedded in tools/launch.py, standalone as tools/gang_kv.py).  Adds
  leases (keys a client stops renewing expire) and watches (blocking
  long-poll on a key prefix), and survives coordinator death by
  deterministic failover: every client keeps a standby socket plus a
  periodically refreshed state frame; the lowest-ranked live client
  re-binds and replays, everyone else reconnects with decorrelated
  jitter and resumes its leases.  No shared filesystem anywhere.
- :class:`CoordKV` — the jax coordination-service key-value store (the
  same gRPC plane `barrier` uses), for real multi-host pods.

`gang_kv()` picks the transport (``MXTPU_GANG_KV=file|tcp``,
``MXTPU_GANG_ADDR``, ``MXTPU_GANG_DIR``).
"""

from __future__ import annotations

import json
import os
import pickle
import socket
import struct
import threading
import time
import zlib

from . import resilience

_INITIALIZED = False


def _rendezvous(coordinator_address, num_processes, process_id):
    """One retried rendezvous attempt loop (coordinator-unreachable is
    the retryable class; the MXTPU_FAULT_INJECT 'rendezvous' site tests
    it hermetically)."""
    import jax

    timeout = float(os.environ.get("MXTPU_RENDEZVOUS_TIMEOUT", 300))
    retries = int(os.environ.get("MXTPU_RENDEZVOUS_RETRIES", 3))

    def attempt():
        resilience.inject_failure("rendezvous")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)

    resilience.retry_call(
        attempt, retries=retries, deadline=timeout, backoff=0.5,
        max_backoff=10.0,
        retryable=(RuntimeError, ConnectionError, OSError,
                   resilience.InjectedFault),
        description=f"rendezvous with {coordinator_address}")


def init_from_env():
    """Join the rendezvous if launch env vars are present; no-op
    otherwise.  Returns True if running distributed."""
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coord = os.environ.get("MXTPU_COORDINATOR")
    if not coord:
        return False
    _rendezvous(coord, int(os.environ["MXTPU_NUM_WORKERS"]),
                int(os.environ["MXTPU_WORKER_RANK"]))
    _INITIALIZED = True
    try:
        from . import telemetry

        telemetry.set_identity(
            rank=int(os.environ["MXTPU_WORKER_RANK"]),
            world=int(os.environ["MXTPU_NUM_WORKERS"]))
    except (ImportError, ValueError):
        pass
    return True


def initialize(coordinator_address=None, num_processes=None,
               process_id=None):
    """Explicit init (reference analog: ps::Postoffice::Start)."""
    global _INITIALIZED
    _rendezvous(coordinator_address, num_processes, process_id)
    _INITIALIZED = True


def rank():
    import jax

    return jax.process_index()


def num_workers():
    import jax

    return jax.process_count()


_BARRIER_N = 0


def _coordination_client():
    """The process's coordination-service client, or None when not
    running distributed (single process / uninitialized)."""
    try:
        from jax._src import distributed as _jdist

        return _jdist.global_state.client
    except Exception:
        return None


def barrier(name="mxtpu_barrier"):
    """Block until every process reaches this barrier.

    Multi-process: uses the coordination-service barrier (gRPC via the
    rendezvous coordinator) — backend-agnostic, so it works where XLA
    cross-process collectives don't exist (the CPU backend used by the
    hermetic 2-process tests).  Single-process: sync_global_devices,
    which also drains in-flight device work.

    Guarded by MXTPU_COLLECTIVE_TIMEOUT: a dead peer produces a stack
    dump and a clean error/abort instead of an infinite hang; the
    barrier's own RPC deadline (2x the watchdog, 1800s unguarded) is the
    defense-in-depth behind it.

    This is also the sync point of checkpoint.AsyncCheckpointer's
    two-phase commit ("ckpt_shards_<step>" — every shard durable before
    rank 0 renames the manifest — and "ckpt_commit_<step>"), so a rank
    that dies mid-checkpoint surfaces here, as a watchdog abort, rather
    than as a torn checkpoint.
    """
    global _BARRIER_N
    with resilience.guard_collective(f"barrier:{name}"):
        client = _coordination_client()
        if client is not None:
            _BARRIER_N += 1
            timeout = float(
                os.environ.get("MXTPU_COLLECTIVE_TIMEOUT") or 900)
            client.wait_at_barrier(f"mxtpu:{name}#{_BARRIER_N}",
                                   timeout_in_ms=int(timeout * 2000))
        else:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(name)


# ---------------------------------------------------------------------------
# Elastic gang control plane (PR 8).
#
# The health plane must keep working *while a member is dead*, which the
# coordination-service barrier above cannot do (wait_at_barrier blocks on
# the dead peer).  So membership state lives in a plain KV store with no
# fate-sharing: writes are per-rank, reads never block on a peer.


class FileKV:
    """Shared-directory key-value store with atomic rename writes.

    Keys are slash-separated paths (``hb/0``, ``epoch/current``,
    ``epoch_ack/3/1``); values are bytes.  A write is tmp-file + rename,
    so readers see either the old or the new value, never a torn one.
    No locks, no daemons: any member (or an outside supervisor like
    ``tools/launch.py --elastic``) can read the gang's state at any
    time, including after every member is dead.
    """

    #: highest-committed-epoch fence, shared by every client of the dir
    _FENCE_KEY = ".epoch_fence"

    def __init__(self, root, rank=None):
        self.root = os.path.abspath(root)
        self.rank = rank
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key):
        if ".." in key.split("/"):
            raise ValueError(f"bad kv key: {key!r}")
        return os.path.join(self.root, *key.split("/"))

    def _check_partition(self):
        if self.rank is not None and \
                resilience.partition_blocked(self.rank):
            raise GangKVError(
                f"rank {self.rank}: injected partition_split (gang dir "
                f"unreachable)")

    def put(self, key, value):
        self._check_partition()
        if isinstance(value, str):
            value = value.encode("utf-8")
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(value)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def get(self, key, default=None):
        self._check_partition()
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except (FileNotFoundError, NotADirectoryError):
            return default

    def committed_epoch(self):
        """The highest epoch any ``put_if_epoch`` committed to this dir."""
        try:
            with open(os.path.join(self.root, self._FENCE_KEY)) as f:
                return int(f.read().strip() or 0)
        except (FileNotFoundError, ValueError):
            return 0

    def put_if_epoch(self, key, value, epoch):
        """Fenced write: reject a mutation carrying an epoch OLDER than
        the highest epoch ever committed through this method (the
        Chubby-style fencing token).  Equal or newer epochs commit and
        advance the fence.  Lock-file + recheck: the fence read, the
        write, and the fence advance happen under an exclusive lock so
        two writers cannot interleave a stale write past a newer
        fence.  Raises :class:`FencedWrite` on rejection."""
        self._check_partition()
        epoch = int(epoch)
        lock = os.path.join(self.root, self._FENCE_KEY + ".lock")
        deadline = time.monotonic() + 5.0
        fd = None
        while fd is None:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                if time.monotonic() > deadline:
                    # a crashed lock holder must not wedge the gang:
                    # break the stale lock and take it
                    try:
                        os.unlink(lock)
                    except FileNotFoundError:
                        pass
                else:
                    time.sleep(0.005)
        try:
            fence = self.committed_epoch()
            if epoch < fence:
                resilience._tel_event(
                    "fencing_rejected", rank=self.rank, epoch=epoch,
                    committed=fence, kind="kv", key=key)
                raise FencedWrite(
                    f"kv put {key!r} fenced: epoch {epoch} < committed "
                    f"epoch {fence}")
            self.put(key, value)
            if epoch > fence:
                fpath = os.path.join(self.root, self._FENCE_KEY)
                tmp = fpath + f".tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    f.write(str(epoch))
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, fpath)
        finally:
            os.close(fd)
            try:
                os.unlink(lock)
            except FileNotFoundError:
                pass

    def put_json_if_epoch(self, key, obj, epoch):
        self.put_if_epoch(key, json.dumps(obj, sort_keys=True), epoch)

    def scan(self, prefix):
        """All (key, value) pairs under ``prefix`` (non-recursive)."""
        self._check_partition()
        base = self._path(prefix)
        try:
            names = sorted(os.listdir(base))
        except (FileNotFoundError, NotADirectoryError):
            return []
        out = []
        for name in names:
            if name.startswith(".") or ".tmp." in name:
                continue
            full = os.path.join(base, name)
            if not os.path.isfile(full):
                continue
            try:
                with open(full, "rb") as f:
                    out.append((f"{prefix}/{name}", f.read()))
            except FileNotFoundError:
                continue
        return out

    def delete(self, key):
        self._check_partition()
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    # JSON convenience layer — everything the gang publishes is JSON.
    def put_json(self, key, obj):
        self.put(key, json.dumps(obj, sort_keys=True))

    def get_json(self, key, default=None):
        raw = self.get(key)
        if raw is None:
            return default
        try:
            return json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return default


class CoordKV:
    """KV plane over the jax coordination service (multi-host pods).

    Best-effort: the coordination service dies with rank 0's process, so
    this transport only covers failures of non-coordinator ranks.  Real
    deployments that need full coverage point MXTPU_GANG_DIR at a shared
    filesystem (or future: an external store) instead.
    """

    def __init__(self, client):
        self._client = client

    def put(self, key, value):
        if isinstance(value, bytes):
            value = value.decode("utf-8", "replace")
        self._client.key_value_set(f"mxtpu_gang/{key}", value,
                                   allow_overwrite=True)

    def get(self, key, default=None):
        getter = getattr(self._client, "key_value_try_get", None)
        if getter is None:
            return default
        try:
            return getter(f"mxtpu_gang/{key}").encode("utf-8")
        except Exception:
            return default

    def scan(self, prefix):
        getter = getattr(self._client, "key_value_dir_get", None)
        if getter is None:
            return []
        try:
            pairs = getter(f"mxtpu_gang/{prefix}/")
        except Exception:
            return []
        out = []
        for key, value in pairs:
            if key.startswith("mxtpu_gang/"):
                key = key[len("mxtpu_gang/"):]
            out.append((key.rstrip("/"), value.encode("utf-8")))
        return out

    def delete(self, key):
        try:
            self._client.key_value_delete(f"mxtpu_gang/{key}")
        except Exception:
            pass

    def put_json(self, key, obj):
        self.put(key, json.dumps(obj, sort_keys=True))

    def get_json(self, key, default=None):
        raw = self.get(key)
        if raw is None:
            return default
        try:
            return json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return default


# ---------------------------------------------------------------------------
# TcpKV: the coordination-service KV over real TCP (PR 12).
#
# Framing is the PR 8 buddy-snapshot idiom (checkpoint.PeerSnapshotStore's
# MXTPSNP1 frames): magic + fixed struct header + CRC32 + pickled payload,
# so a torn or corrupted frame is a clean error, never a mis-parse.


_KV_MAGIC = b"MXTPGKV1"
_KV_HDR = struct.Struct("<BIQ")   # code u8 | crc32 u32 | payload_len u64
_KV_MAX_FRAME = 64 << 20          # control-plane values are small

(_OP_PUT, _OP_GET, _OP_SCAN, _OP_DEL, _OP_RENEW, _OP_WATCH,
 _OP_STATE, _OP_PING, _OP_PUT_IF_EPOCH) = range(1, 10)
_ST_OK, _ST_ERR = 0, 1

_OP_NAMES = {_OP_PUT: "put", _OP_GET: "get", _OP_SCAN: "scan",
             _OP_DEL: "delete", _OP_RENEW: "renew", _OP_WATCH: "watch",
             _OP_STATE: "state", _OP_PING: "ping",
             _OP_PUT_IF_EPOCH: "put_if_epoch"}

#: server-side error prefix a fenced mutation comes back with; the
#: client turns it into :class:`FencedWrite` instead of retrying
_FENCED_ERR = "fenced:"


class GangKVError(resilience.MXNetError):
    """The TCP gang KV could not complete an operation (after retries
    and failover attempts) — or a `net_partition` / `partition_split`
    fault is armed for this rank."""


class FencedWrite(resilience.MXNetError):
    """A ``put_if_epoch`` mutation carried an epoch older than the
    highest committed one: the writer is on the losing side of a
    reshape (zombie or partition minority) and must not mutate shared
    state.  Deliberately NOT retryable — the fence only moves forward."""


def _recv_exact(conn, n):
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("gang kv: peer closed mid-frame")
        buf += chunk
    return buf


def _kv_send(conn, code, obj):
    payload = pickle.dumps(obj, protocol=4)
    hdr = _KV_HDR.pack(code, zlib.crc32(payload) & 0xFFFFFFFF,
                       len(payload))
    conn.sendall(_KV_MAGIC + hdr + payload)


def _kv_recv(conn):
    raw = _recv_exact(conn, len(_KV_MAGIC) + _KV_HDR.size)
    if raw[:len(_KV_MAGIC)] != _KV_MAGIC:
        raise ConnectionError("gang kv: bad frame magic")
    code, crc, length = _KV_HDR.unpack(raw[len(_KV_MAGIC):])
    if length > _KV_MAX_FRAME:
        raise ConnectionError(f"gang kv: oversized frame ({length} B)")
    payload = _recv_exact(conn, length)
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise ConnectionError("gang kv: frame CRC mismatch")
    return code, pickle.loads(payload)


def _check_kv_key(key):
    if ".." in key.split("/"):
        raise ValueError(f"bad kv key: {key!r}")
    return key


def lease_ttl_from_env(default=10.0):
    try:
        return max(0.1, float(os.environ.get("MXTPU_LEASE_TTL", default)))
    except ValueError:
        return default


class GangKVServer:
    """Stdlib-only gang KV daemon: a dict + leases + watch conditions
    behind the framed TCP protocol above.

    - keys → bytes, exactly the FileKV namespace; ``scan`` is
      non-recursive (direct children only), sorted.
    - leases: a PUT may carry a lease id; a sweeper deletes every key of
      a lease whose client stopped renewing for ``lease_ttl`` — the
      heartbeat files' mtime-freshness, without a filesystem.
    - watches: every mutation bumps a global version and notifies; a
      WATCH long-polls until some key under its prefix changes past the
      version the client last saw.
    - failover seeding: ``state=``/``version=`` restart the store from a
      client's cached STATE frame (the promoted coordinator's replay);
      ``sock=`` serves on a pre-bound standby socket.

    The ``kill_coordinator`` fault site makes the daemon drop dead on
    the next mutation — mid-protocol, connections cut, no reply — which
    is exactly what the client failover path must survive.
    """

    def __init__(self, host="127.0.0.1", port=0, *, lease_ttl=None,
                 state=None, version=0, leases=None, sock=None,
                 fence=0):
        self.lease_ttl = (lease_ttl_from_env() if lease_ttl is None
                          else float(lease_ttl))
        self._fence = int(fence)    # highest committed gang epoch
        self._data = {}
        for k, v in (state or {}).items():
            self._data[k] = v if isinstance(v, bytes) else \
                str(v).encode("utf-8")
        self._ver = int(version)
        self._key_ver = {k: self._ver for k in self._data}
        now = time.monotonic()
        self._leases = {}
        for lid, keys in (leases or {}).items():
            self._leases[lid] = {"deadline": now + self.lease_ttl,
                                 "keys": set(keys)}
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._conns = set()
        self._threads = []
        self.requests = 0
        self.died = False           # killed by fault injection
        if sock is not None:
            self._sock = sock
        else:
            self._sock = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
            self._sock.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
            self._sock.bind((host, int(port)))
        self.host, self.port = self._sock.getsockname()[:2]
        self._accept_thread = None

    @property
    def addr(self):
        return f"{self.host}:{self.port}"

    def start(self):
        if self._accept_thread is None:
            self._sock.listen(64)
            self._sock.settimeout(0.2)
            self._accept_thread = threading.Thread(
                target=self._serve, name=f"gang-kv:{self.port}",
                daemon=True)
            self._accept_thread.start()
        return self

    def stop(self):
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        try:
            self._sock.close()
        except OSError:
            pass
        for c in list(self._conns):
            try:
                c.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)

    # alias: a killed coordinator and a stopped one look the same to
    # clients; tests use die() to simulate coordinator death in-process
    def die(self):
        self.died = True
        self.stop()

    def _serve(self):
        while not self._stop.is_set():
            self._sweep_leases()
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.settimeout(60.0)
            self._conns.add(conn)
            t = threading.Thread(target=self._handle, args=(conn,),
                                 daemon=True)
            t.start()

    def _sweep_leases(self):
        now = time.monotonic()
        with self._cond:
            expired = [lid for lid, l in self._leases.items()
                       if l["deadline"] < now]
            changed = False
            for lid in expired:
                for k in self._leases.pop(lid)["keys"]:
                    if k in self._data:
                        del self._data[k]
                        self._ver += 1
                        self._key_ver[k] = self._ver
                        changed = True
            if changed:
                self._cond.notify_all()

    def _handle(self, conn):
        try:
            while not self._stop.is_set():
                try:
                    code, args = _kv_recv(conn)
                except (ConnectionError, OSError, EOFError,
                        pickle.UnpicklingError):
                    return
                self.requests += 1
                if code in (_OP_PUT, _OP_DEL) and \
                        resilience.consume_charges("kill_coordinator"):
                    # fires on the LAST charge: the Nth mutation of a
                    # kill_coordinator:N plan
                    # injected coordinator death: cut every client off
                    # mid-request, no reply — the worst-timed crash
                    self.die()
                    return
                try:
                    resp = self._dispatch(code, args)
                except ValueError as e:
                    _kv_send(conn, _ST_ERR, f"{e}")
                    continue
                try:
                    _kv_send(conn, _ST_OK, resp)
                except OSError:
                    return
        finally:
            self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, code, args):
        if code == _OP_PUT:
            key, value, lease_id = args
            _check_kv_key(key)
            with self._cond:
                self._data[key] = value
                self._ver += 1
                self._key_ver[key] = self._ver
                if lease_id:
                    lease = self._leases.setdefault(
                        lease_id, {"deadline": 0.0, "keys": set()})
                    lease["keys"].add(key)
                    lease["deadline"] = time.monotonic() + self.lease_ttl
                self._cond.notify_all()
                return self._ver
        if code == _OP_PUT_IF_EPOCH:
            key, value, epoch = args
            _check_kv_key(key)
            epoch = int(epoch)
            with self._cond:
                if epoch < self._fence:
                    raise ValueError(
                        f"{_FENCED_ERR} put {key!r} epoch {epoch} < "
                        f"committed epoch {self._fence}")
                self._data[key] = value
                self._ver += 1
                self._key_ver[key] = self._ver
                self._fence = max(self._fence, epoch)
                self._cond.notify_all()
                return self._ver
        if code == _OP_GET:
            with self._cond:
                return self._data.get(args[0])
        if code == _OP_SCAN:
            pref = args[0].rstrip("/") + "/"
            with self._cond:
                return [(k, self._data[k]) for k in sorted(self._data)
                        if k.startswith(pref)
                        and "/" not in k[len(pref):]]
        if code == _OP_DEL:
            key = args[0]
            with self._cond:
                if key in self._data:
                    del self._data[key]
                    self._ver += 1
                    self._key_ver[key] = self._ver
                    self._cond.notify_all()
                for lease in self._leases.values():
                    lease["keys"].discard(key)
                return self._ver
        if code == _OP_RENEW:
            lease_id, keys = args
            with self._cond:
                lease = self._leases.setdefault(
                    lease_id, {"deadline": 0.0, "keys": set()})
                lease["deadline"] = time.monotonic() + self.lease_ttl
                lease["keys"] |= {k for k in keys if k in self._data}
                return self._ver
        if code == _OP_WATCH:
            prefix, since, timeout = args
            deadline = time.monotonic() + min(float(timeout), 30.0)
            with self._cond:
                start = self._ver if since is None else int(since)
                while not self._stop.is_set():
                    if any(v > start for k, v in self._key_ver.items()
                           if k.startswith(prefix)):
                        break
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._cond.wait(min(left, 0.5))
                return self._ver
        if code == _OP_STATE:
            with self._cond:
                return (self._ver, dict(self._data),
                        {lid: sorted(l["keys"])
                         for lid, l in self._leases.items()},
                        self._fence)
        if code == _OP_PING:
            return self._ver
        raise ValueError(f"gang kv: unknown op {code}")


class TcpKV:
    """FileKV-compatible client for :class:`GangKVServer`.

    Same ``put/get/scan/delete`` + JSON surface, plus:

    - leases: keys under ``ephemeral_prefixes`` (heartbeats, failover
      candidacy) are attached to this client's lease and renewed by a
      background thread; when the process dies the server expires them
      — the replacement for heartbeat-file mtime freshness.
    - ``watch(prefix)``: blocking long-poll until a key under the
      prefix changes — the replacement for directory rescans.
    - coordinator failover: the client keeps (a) a standby socket bound
      at construction and advertised at ``failover/<rank>``, (b) a state
      frame refreshed on every lease renewal, and (c) an LRU of its own
      recent writes.  When the coordinator dies, each retry pings the
      standby addresses of lower-ranked clients; the lowest live rank
      promotes itself (re-binds, replays the state frame), everyone
      else adopts the promoted address and replays its own writes —
      which is also what re-proposes an interrupted epoch proposal
      (epoch/current is one of the proposer's recent writes).
    """

    _REPLAY_KEYS = 256   # per-client write LRU replayed after failover

    def __init__(self, addr=None, *, rank=None, lease_ttl=None,
                 ephemeral_prefixes=("hb/", "failover/"), standby=None,
                 timeout=None):
        addr = addr or os.environ.get("MXTPU_GANG_ADDR")
        if not addr:
            raise resilience.MXNetError(
                "TcpKV needs an address (MXTPU_GANG_ADDR=host:port)")
        host, _, port = addr.rpartition(":")
        self._host, self._port = host or "127.0.0.1", int(port)
        if rank is None:
            r = os.environ.get("MXTPU_WORKER_RANK")
            rank = int(r) if r is not None else None
        self.rank = rank
        self._timeout = float(
            os.environ.get("MXTPU_KV_TIMEOUT", 5.0)
            if timeout is None else timeout)
        self._ttl = (lease_ttl_from_env() if lease_ttl is None
                     else float(lease_ttl))
        self._eph = tuple(ephemeral_prefixes)
        self._lease_id = (f"r{rank if rank is not None else 'x'}."
                          f"{os.getpid()}.{os.urandom(3).hex()}")
        self._stagger = float(
            os.environ.get("MXTPU_KV_FAILOVER_STAGGER", 0.5))
        self._retries = int(os.environ.get("MXTPU_KV_RETRIES", 10))
        # total-elapsed retry budget (s): bounds partition-era retries so
        # callers fail over to fencing checks instead of spinning forever
        try:
            self._max_elapsed = float(
                os.environ.get("MXTPU_KV_MAX_ELAPSED", "0")) or None
        except ValueError:
            self._max_elapsed = None
        self._conn = None
        self._conn_lock = threading.Lock()
        self._state_lock = threading.Lock()
        self._state = ({}, 0, 0)     # (data, version, fence) — failover seed
        self._written = {}           # key -> value LRU (failover replay)
        self._leased = set()
        self._down_since = None
        self._fo_lock = threading.Lock()
        self._server = None          # set if this client promoted
        self.failovers = 0
        self.closed = False
        self._standby = None
        if standby is None:
            standby = rank is not None
        if standby:
            self._standby = socket.socket(socket.AF_INET,
                                          socket.SOCK_STREAM)
            self._standby.setsockopt(socket.SOL_SOCKET,
                                     socket.SO_REUSEADDR, 1)
            # bound but NOT listening: pings get ECONNREFUSED until the
            # promotion actually happens
            self._standby.bind((self._host if self._host != "0.0.0.0"
                                else "127.0.0.1", 0))
        self._stop = threading.Event()
        self._renewer = threading.Thread(
            target=self._renew_loop, name=f"gang-kv-lease:{rank}",
            daemon=True)
        self._renewer.start()
        if self._standby is not None:
            sh, sp = self._standby.getsockname()[:2]
            try:
                self.put_json(f"failover/{self.rank}",
                              {"rank": self.rank, "host": sh,
                               "port": sp})
            except Exception:   # noqa: BLE001 — registered on reconnect
                pass
        try:
            self._refresh_state()
        except Exception:       # noqa: BLE001 — refreshed by renewals
            pass

    # -- transport -------------------------------------------------------------

    def _connect(self):
        conn = socket.create_connection(
            (self._host, self._port), timeout=self._timeout)
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return conn

    def _rpc(self, op, args, timeout=None):
        with self._conn_lock:
            try:
                if self._conn is None:
                    self._conn = self._connect()
                self._conn.settimeout(timeout or self._timeout)
                _kv_send(self._conn, op, args)
                code, obj = _kv_recv(self._conn)
            except (OSError, EOFError, ConnectionError,
                    pickle.UnpicklingError) as e:
                if self._conn is not None:
                    try:
                        self._conn.close()
                    except OSError:
                        pass
                    self._conn = None
                raise ConnectionError(f"gang kv rpc failed: {e}") from e
        if code == _ST_ERR:
            raise ValueError(str(obj))
        self._down_since = None
        return obj

    def _call(self, op, *args, timeout=None):
        if self.rank is not None and \
                self.rank in resilience.fault_args("net_partition"):
            raise GangKVError(
                f"rank {self.rank}: injected net partition")
        if self.rank is not None and \
                resilience.partition_blocked(self.rank):
            raise GangKVError(
                f"rank {self.rank}: injected partition_split "
                f"(coordinator unreachable)")

        def attempt():
            return self._rpc(op, args, timeout=timeout)

        def on_retry(_attempt, _exc, _sleep):
            self._maybe_failover()

        try:
            return resilience.retry_call(
                attempt, retries=self._retries, backoff=0.05,
                max_backoff=0.5, jitter=True,
                max_elapsed=self._max_elapsed,
                retryable=(ConnectionError, OSError),
                on_retry=on_retry,
                description=f"gang kv {_OP_NAMES.get(op, op)}")
        except (ConnectionError, OSError) as e:
            raise GangKVError(f"gang kv unreachable at "
                              f"{self._host}:{self._port}: {e}") from e

    # -- failover --------------------------------------------------------------

    def _refresh_state(self):
        frame = self._rpc(_OP_STATE, ())
        ver, data, leases = frame[:3]
        fence = frame[3] if len(frame) > 3 else 0
        with self._state_lock:
            self._state = (data, ver, fence)
        return ver

    def _candidates(self):
        with self._state_lock:
            data = dict(self._state[0])
        cands = []
        for key, raw in data.items():
            if not key.startswith("failover/"):
                continue
            try:
                rec = json.loads(raw.decode("utf-8"))
                cands.append((int(rec["rank"]), rec["host"],
                              int(rec["port"])))
            except (ValueError, KeyError, UnicodeDecodeError):
                continue
        return sorted(cands)

    def _maybe_failover(self):
        now = time.monotonic()
        if self._down_since is None:
            self._down_since = now
            return
        with self._fo_lock:
            if self._server is not None:
                return      # already promoted; retries hit our server
            cands = self._candidates()
            for idx, (r, host, port) in enumerate(cands):
                if r == self.rank:
                    if now - self._down_since >= idx * self._stagger:
                        self._promote()
                    return
                try:
                    conn = socket.create_connection((host, port),
                                                    timeout=0.25)
                    try:
                        _kv_send(conn, _OP_PING, ())
                        _kv_recv(conn)
                    finally:
                        conn.close()
                except (OSError, ConnectionError):
                    continue
                self._adopt(host, port)
                return

    def _promote(self):
        """Become the coordinator: listen on the standby socket, replay
        the last state frame, then replay our own recent writes."""
        if self._standby is None:
            return
        with self._state_lock:
            data, ver, fence = (dict(self._state[0]), self._state[1],
                                self._state[2])
        srv = GangKVServer(lease_ttl=self._ttl, state=data,
                           version=ver + 1, sock=self._standby,
                           fence=fence)
        srv.start()
        self._server = srv
        self._standby = None
        self._host, self._port = srv.host, srv.port
        self._down_since = None
        self.failovers += 1
        resilience._tel_event("coordinator_failover", rank=self.rank,
                              addr=srv.addr, role="promoted",
                              replayed_keys=len(data))
        self._replay_writes()

    def _adopt(self, host, port):
        self._host, self._port = host, port
        self._down_since = None
        self.failovers += 1
        resilience._tel_event("coordinator_reconnect", rank=self.rank,
                              addr=f"{host}:{port}")
        self._replay_writes()

    def _replay_writes(self):
        """Re-put this client's recent writes against the (new)
        coordinator: resumes our leases and re-proposes any epoch record
        this rank was mid-writing when the old coordinator died."""
        for key, value in list(self._written.items()):
            lease = self._lease_id if self._is_ephemeral(key) else None
            try:
                self._rpc(_OP_PUT, (key, value, lease))
            except (ConnectionError, OSError, ValueError):
                return

    # -- lease renewal ---------------------------------------------------------

    def _renew_loop(self):
        interval = max(0.05, min(self._ttl / 3.0, 2.0))
        last_ver = -1
        while not self._stop.wait(interval):
            try:
                ver = self._call(_OP_RENEW,
                                 self._lease_id, sorted(self._leased))
                if ver != last_ver:
                    last_ver = self._refresh_state()
            except Exception:   # noqa: BLE001 — next op retries/fails over
                pass

    def _is_ephemeral(self, key):
        return any(key.startswith(p) for p in self._eph)

    # -- the FileKV surface ----------------------------------------------------

    def put(self, key, value):
        _check_kv_key(key)
        if isinstance(value, str):
            value = value.encode("utf-8")
        lease = None
        if self._is_ephemeral(key):
            lease = self._lease_id
            self._leased.add(key)
        self._written[key] = value
        if len(self._written) > self._REPLAY_KEYS:
            self._written.pop(next(iter(self._written)))
        self._call(_OP_PUT, key, value, lease)

    def put_if_epoch(self, key, value, epoch):
        """Fenced write (server-side check): rejected with
        :class:`FencedWrite` when ``epoch`` is older than the highest
        epoch any client committed.  Fenced keys are deliberately kept
        OUT of the failover-replay LRU — replaying a stale epoch record
        after a partition heals is exactly the split-brain vector the
        fence exists to close."""
        _check_kv_key(key)
        if isinstance(value, str):
            value = value.encode("utf-8")
        try:
            return self._call(_OP_PUT_IF_EPOCH, key, value, int(epoch))
        except ValueError as e:
            if str(e).startswith(_FENCED_ERR):
                resilience._tel_event(
                    "fencing_rejected", rank=self.rank,
                    epoch=int(epoch), kind="kv", key=key)
                raise FencedWrite(str(e)) from e
            raise

    def put_json_if_epoch(self, key, obj, epoch):
        return self.put_if_epoch(key, json.dumps(obj, sort_keys=True),
                                 epoch)

    def committed_epoch(self):
        """The coordinator's highest committed gang epoch (the fence).

        The full state frame comes back with the answer, so it also
        refreshes this client's failover seed — a promotion right
        after a fence check replays the fence it just read, instead of
        a frame from the last (possibly seconds-old) lease renewal."""
        frame = self._call(_OP_STATE)
        ver, data, _leases = frame[:3]
        fence = frame[3] if len(frame) > 3 else 0
        with self._state_lock:
            self._state = (data, ver, fence)
        return fence

    def get(self, key, default=None):
        _check_kv_key(key)
        value = self._call(_OP_GET, key)
        return default if value is None else value

    def scan(self, prefix):
        return [(k, v) for k, v in self._call(_OP_SCAN, prefix)]

    def delete(self, key):
        self._written.pop(key, None)
        self._leased.discard(key)
        self._call(_OP_DEL, key)

    def put_json(self, key, obj):
        self.put(key, json.dumps(obj, sort_keys=True))

    def get_json(self, key, default=None):
        raw = self.get(key)
        if raw is None:
            return default
        try:
            return json.loads(raw.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return default

    # -- extras over FileKV ----------------------------------------------------

    def watch(self, prefix, since=None, timeout=1.0):
        """Block until a key under ``prefix`` changes (or ``timeout``).
        Returns the server's version counter — pass it back as
        ``since`` to never miss a change between calls.  Best-effort:
        returns ``since`` on transport failure (callers fall back to
        their polling loop).  Uses a dedicated connection so a long
        poll never blocks the pooled one (heartbeats keep flowing)."""
        try:
            conn = self._connect()
            try:
                conn.settimeout(timeout + self._timeout)
                _kv_send(conn, _OP_WATCH, (prefix, since, timeout))
                code, obj = _kv_recv(conn)
            finally:
                conn.close()
            if code == _ST_ERR:
                raise ValueError(str(obj))
            return obj
        except (ConnectionError, OSError):
            self._maybe_failover()
            return since

    def ping(self):
        return self._call(_OP_PING)

    def close(self, stop_server=True):
        self.closed = True
        self._stop.set()
        self._renewer.join(timeout=2.0)
        with self._conn_lock:
            if self._conn is not None:
                try:
                    self._conn.close()
                except OSError:
                    pass
                self._conn = None
        if self._standby is not None:
            try:
                self._standby.close()
            except OSError:
                pass
        if stop_server and self._server is not None:
            self._server.stop()


_TCP_KV_CACHE = {}


def _tcp_gang_kv(addr):
    """Per-process TcpKV singleton: one lease + one standby socket per
    (address, rank), however many times gang_kv() is called."""
    rank = os.environ.get("MXTPU_WORKER_RANK")
    key = (addr, rank)
    kv = _TCP_KV_CACHE.get(key)
    if kv is None or kv.closed:
        kv = TcpKV(addr)
        _TCP_KV_CACHE[key] = kv
    return kv


def gang_kv():
    """The elastic control plane's KV transport, or None when elastic
    recovery has nowhere to publish (no gang dir/addr, not
    distributed).  Selection: ``MXTPU_GANG_KV=file|tcp`` explicitly;
    otherwise ``MXTPU_GANG_ADDR`` ⇒ tcp, ``MXTPU_GANG_DIR`` ⇒ file
    (dir wins when both are set and no explicit choice was made),
    else the coordination-service KV."""
    mode = (os.environ.get("MXTPU_GANG_KV") or "").strip().lower()
    addr = os.environ.get("MXTPU_GANG_ADDR")
    root = os.environ.get("MXTPU_GANG_DIR")
    if mode not in ("", "file", "tcp"):
        raise resilience.MXNetError(
            f"MXTPU_GANG_KV must be 'file' or 'tcp', got {mode!r}")
    if mode == "tcp" or (not mode and addr and not root):
        if not addr:
            raise resilience.MXNetError(
                "MXTPU_GANG_KV=tcp needs MXTPU_GANG_ADDR=host:port")
        return _tcp_gang_kv(addr)
    if mode == "file" and not root:
        raise resilience.MXNetError(
            "MXTPU_GANG_KV=file needs MXTPU_GANG_DIR")
    if root:
        r = os.environ.get("MXTPU_WORKER_RANK")
        return FileKV(root, rank=int(r) if r is not None else None)
    client = _coordination_client()
    if client is not None and hasattr(client, "key_value_set"):
        return CoordKV(client)
    return None
