"""Multi-host process bootstrap.

Reference parity: ps-lite's Postoffice/Van rendezvous + the dmlc tracker
env contract (DMLC_ROLE/DMLC_PS_ROOT_URI/DMLC_NUM_WORKER, SURVEY.md §5.6
plane 4).

TPU-native: one coordinator rendezvous via ``jax.distributed.initialize``;
the env contract is MXTPU_COORDINATOR / MXTPU_NUM_WORKERS /
MXTPU_WORKER_RANK, set by tools/launch.py.  After init, every process sees
the global device set and collectives span hosts over ICI/DCN
automatically.  Checkpoint-restart is the recovery primitive (SURVEY.md
§5.3: elasticity is out of scope, matching the reference).
"""

from __future__ import annotations

import os

_INITIALIZED = False


def init_from_env():
    """Join the rendezvous if launch env vars are present; no-op
    otherwise.  Returns True if running distributed."""
    global _INITIALIZED
    if _INITIALIZED:
        return True
    coord = os.environ.get("MXTPU_COORDINATOR")
    if not coord:
        return False
    import jax

    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["MXTPU_NUM_WORKERS"]),
        process_id=int(os.environ["MXTPU_WORKER_RANK"]))
    _INITIALIZED = True
    return True


def initialize(coordinator_address=None, num_processes=None,
               process_id=None):
    """Explicit init (reference analog: ps::Postoffice::Start)."""
    global _INITIALIZED
    import jax

    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id)
    _INITIALIZED = True


def rank():
    import jax

    return jax.process_index()


def num_workers():
    import jax

    return jax.process_count()


def barrier(name="mxtpu_barrier"):
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
