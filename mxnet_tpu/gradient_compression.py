"""Gradient compression for KVStore synchronization.

Reference parity: src/kvstore/gradient_compression.cc /
gradient_compression-inl.h — threshold-based 2-bit quantization with
per-key error feedback (residual accumulation), enabled via
``kvstore.set_gradient_compression({'type': '2bit', 'threshold': t})``.

TPU-first redesign: the reference quantizes worker→server pushes to cut
PS/TCP bandwidth; here the expensive hop is DCN between hosts, and the
collective is GSPMD.  ``2bit`` packs four 2-bit codes per uint8 and the
cross-process exchange becomes an all-gather of the PACKED codes (W ×
n/4 bytes on the wire instead of W-1 rounds of dense bf16/f32 ring
all-reduce), decoded and summed on-device inside one jitted program.
An ``fp16`` mode (half-precision transfer with error feedback) is also
provided.  Quantization semantics match the reference exactly:

    q_i =  +threshold   if (g+r)_i >= threshold
           -threshold   if (g+r)_i <= -threshold
           0            otherwise
    r   <- (g+r) - q          (error feedback)
"""

from __future__ import annotations

from .base import MXNetError

_SHIFTS = (0, 2, 4, 6)


class GradientCompression:
    """Per-KVStore compression state: type, threshold, per-key residuals."""

    # Bucketed all-reduce (KVStore.bucketed_pushpull) concatenates many
    # keys into one flat buffer, but every mode here keeps a PER-KEY
    # error-feedback residual whose shape is the key's own — compressing
    # a bucket would silently merge residuals across keys.  KVStore
    # therefore drops to the per-key pushpull path whenever compression
    # is active.
    supports_bucketing = False

    def __init__(self, params):
        params = dict(params or {})
        self.type = params.pop("type", "2bit")
        self.threshold = float(params.pop("threshold", 0.5))
        if params:
            raise MXNetError(
                f"unknown gradient compression params {sorted(params)}")
        if self.type not in ("2bit", "fp16"):
            raise MXNetError(
                f"gradient compression type '{self.type}' is not "
                "supported (2bit, fp16)")
        if self.type == "2bit" and self.threshold <= 0:
            raise MXNetError("2bit compression needs a positive threshold")
        self._residual = {}  # key -> raw residual array

    # -- quantization (local, with error feedback) -----------------------------

    def _accumulate(self, key, grad):
        r = self._residual.get(key)
        return grad if r is None else grad + r

    def _threshold_quantize(self, acc, dtype):
        """(pos_mask, neg_mask, q) for the 2-bit threshold rule."""
        import jax.numpy as jnp

        t = jnp.asarray(self.threshold, dtype)
        pos = acc >= t
        neg = acc <= -t
        q = jnp.where(pos, t, jnp.where(neg, -t, jnp.zeros((), dtype)))
        return pos, neg, q

    def quantize(self, key, grad):
        """Return the dequantized-on-this-worker gradient contribution and
        update the residual.  ``grad`` is a raw jax array."""
        import jax.numpy as jnp

        acc = self._accumulate(key, grad)
        if self.type == "fp16":
            q = acc.astype(jnp.float16).astype(grad.dtype)
        else:
            _, _, q = self._threshold_quantize(acc, grad.dtype)
        self._residual[key] = acc - q
        return q

    def quantize_fp16_wire(self, key, grad):
        """fp16 mode: return the HALF-precision array itself so the
        cross-process exchange carries f16 bytes — casting back to
        grad.dtype before the all-reduce (the old path) made the
        documented half-precision transfer save no DCN bandwidth.
        Error feedback matches quantize(): the residual holds what the
        f16 rounding lost."""
        import jax.numpy as jnp

        assert self.type == "fp16"
        acc = self._accumulate(key, grad)
        h = acc.astype(jnp.float16)
        self._residual[key] = acc - h.astype(grad.dtype)
        return h

    def quantize_rowsparse(self, key, ids, vals):
        """Error feedback for a compact row-sparse gradient: quantize
        over the UNION of this gradient's rows and the rows still owing
        residual, and keep the residual itself compact — a row no batch
        ever touched has exactly zero error and is never materialized
        (the dense-view path would scatter threshold noise into cold
        embedding rows).  Returns ``(union_ids, q_vals)``; rows whose
        residual quantizes away are pruned from the carry."""
        import jax.numpy as jnp

        ids = jnp.asarray(ids, jnp.int32)
        vals = jnp.asarray(vals)
        # coalesce duplicates and sort rows by id (searchsorted below
        # needs sorted ids; jnp.unique returns them sorted)
        uid, inv = jnp.unique(ids, return_inverse=True)
        vals = jnp.zeros((uid.shape[0],) + vals.shape[1:],
                         vals.dtype).at[inv.reshape(-1)].add(vals)
        ids = uid
        prev = self._residual.get(key)
        if prev is None:
            union, acc = ids, vals
        else:
            pids, pvals = prev
            union = jnp.union1d(pids, ids)
            acc = jnp.zeros((union.shape[0],) + vals.shape[1:],
                            vals.dtype)
            acc = acc.at[jnp.searchsorted(union, pids)].add(pvals)
            acc = acc.at[jnp.searchsorted(union, ids)].add(vals)
        if self.type == "fp16":
            q = acc.astype(jnp.float16).astype(vals.dtype)
        else:
            _, _, q = self._threshold_quantize(acc, vals.dtype)
        res = acc - q
        owing = jnp.any(res != 0, axis=tuple(range(1, res.ndim)))
        keep = jnp.nonzero(owing)[0]  # eager path: host sync is fine
        if keep.shape[0]:
            self._residual[key] = (union[keep], res[keep])
        else:
            self._residual.pop(key, None)
        return union, q

    def codes(self, key, grad):
        """2bit only: quantize with error feedback and return PACKED uint8
        codes (4 values/byte) for the wire."""
        import jax.numpy as jnp

        assert self.type == "2bit"
        acc = self._accumulate(key, grad)
        pos, neg, q = self._threshold_quantize(acc, grad.dtype)
        self._residual[key] = acc - q
        c = jnp.where(pos, jnp.uint8(1),
                      jnp.where(neg, jnp.uint8(2), jnp.uint8(0)))
        flat = c.reshape(-1)
        pad = (-flat.shape[0]) % 4
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.uint8)])
        quads = flat.reshape(-1, 4)
        packed = (quads[:, 0] | (quads[:, 1] << 2)
                  | (quads[:, 2] << 4) | (quads[:, 3] << 6))
        return packed.astype(jnp.uint8)

    @staticmethod
    def decode_sum(packed_rows, n, threshold, dtype):
        """Decode a (W, n/4) stack of packed code rows and sum the
        dequantized contributions → dense (n,).  jit-traceable."""
        import jax.numpy as jnp

        shifts = jnp.asarray(_SHIFTS, jnp.uint8)
        bits = (packed_rows[:, :, None] >> shifts[None, None, :]) & 3
        codes = bits.reshape(packed_rows.shape[0], -1)[:, :n]
        t = jnp.asarray(threshold, dtype)
        vals = jnp.where(codes == 1, t,
                         jnp.where(codes == 2, -t, jnp.zeros((), dtype)))
        return vals.sum(axis=0, dtype=dtype)

    def reset(self):
        self._residual.clear()
