"""Symbolic graph construction.

Reference parity: python/mxnet/symbol/ + 3rdparty nnvm Symbol/Graph
(include/nnvm/symbolic.h) — mx.sym.Variable, generated op symbols,
list_arguments/list_outputs/infer_shape, tojson/load, bind/simple_bind,
Symbol.eval, Group.

TPU-first redesign: a Symbol is a lightweight Python DAG over the SAME op
registry the imperative API uses; "binding" compiles the whole graph with
``jax.jit`` (shape inference = jax.eval_shape — no hand-written FInferShape
pass).  The JSON format keeps the reference's structural layout
({'nodes': [...], 'arg_nodes': [...], 'heads': [...]}) so exported
symbol.json files are recognizable and round-trip.
"""

from __future__ import annotations

import json

from ..attribute import current_attrs as _current_attrs
from ..base import MXNetError
from ..ops import registry as _registry

_SYM_COUNTER = [0]


def _auto_name(hint):
    _SYM_COUNTER[0] += 1
    return f"{hint.lower()}{_SYM_COUNTER[0] - 1}"


class Symbol:
    """One output of a graph node (reference: nnvm NodeEntry + Symbol)."""

    __slots__ = ("op", "name", "inputs", "attrs", "out_index", "_n_outputs",
                 "_attr_dict")

    def __init__(self, op, name, inputs, attrs, out_index=0, n_outputs=1):
        self.op = op                  # None for variables
        self.name = name
        self.inputs = inputs          # list[Symbol]
        self.attrs = attrs            # op kwargs (json-serializable)
        self.out_index = out_index
        self._n_outputs = n_outputs
        self._attr_dict = {}

    # -- construction ----------------------------------------------------------

    def __repr__(self):
        return f"<Symbol {self.name}>"

    def __copy__(self):
        return Symbol(self.op, self.name, list(self.inputs),
                      dict(self.attrs), self.out_index, self._n_outputs)

    def attr(self, key):
        return self._attr_dict.get(key)

    @property
    def shape(self):
        """Static shape when known (Variables created from Parameters
        or shaped trace inputs); models read e.g. ``ids.shape[1]`` in
        hybrid_forward, and the export trace must serve it."""
        s = self._attr_dict.get("shape")
        if s is None:
            raise AttributeError(
                f"Symbol {self.name!r} has no static shape (create the "
                "Variable with shape=, or export after a forward pass "
                "so trace inputs carry the seen shapes)")
        return tuple(s)

    def _set_attr(self, **kwargs):
        # skip Nones: var()'s absent kwarg defaults must not clobber
        # AttrScope-provided values (lr_mult etc.)
        self._attr_dict.update(
            {k: v for k, v in kwargs.items() if v is not None})

    def __getitem__(self, index):
        if not isinstance(index, int):
            # array indexing (slices/tuples, e.g. pos_table[:T] or
            # seq[:, 0, :]) becomes a graph node with a JSON-able spec.
            # INT indexing keeps its historical output-view meaning
            # (loaded multi-output graphs depend on it) — use [i:i+1] /
            # slice_axis for row selection.
            return apply_op("_sym_index", self,
                            index_spec=_encode_index(index))
        if self._n_outputs == 1 and index == 0:
            return self
        view = Symbol(self.op, self.name, self.inputs, self.attrs,
                      out_index=index, n_outputs=self._n_outputs)
        # attrs are NODE-level (eval caches by name): views share
        # the dict so e.g. a partitioned region's carried state is
        # reachable through any output view
        view._attr_dict = self._attr_dict
        return view

    # arithmetic via registered broadcast ops
    def _binop(self, other, opname, reverse=False):
        if not isinstance(other, Symbol):
            other = _scalar_sym(other)
        a, b = (other, self) if reverse else (self, other)
        return apply_op(opname, a, b)

    def __add__(self, o):
        return self._binop(o, "broadcast_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "broadcast_sub")

    def __rsub__(self, o):
        return self._binop(o, "broadcast_sub", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "broadcast_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "broadcast_div")

    def __rtruediv__(self, o):
        return self._binop(o, "broadcast_div", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power")

    def __neg__(self):
        return apply_op("negative", self)

    # -- graph introspection ---------------------------------------------------

    def _topo(self):
        order, seen = [], set()
        stack = [self]
        while stack:
            node = stack.pop()
            if id(node) in seen:
                continue
            pending = [i for i in node.inputs if id(i) not in seen]
            if pending:
                stack.append(node)
                # reversed → leftmost input resolves first (reference
                # argument ordering: data before weights before labels)
                stack.extend(reversed(pending))
            else:
                seen.add(id(node))
                order.append(node)
        return order

    def list_arguments(self):
        """Free variables in topo order, aux excluded (reference:
        Symbol.list_arguments)."""
        return [n.name for n in self._topo()
                if n.op is None and not n._attr_dict.get("__aux__")
                and "__scalar__" not in n.attrs
                and "__null__" not in n.attrs]

    def list_auxiliary_states(self):
        return [n.name for n in self._topo()
                if n.op is None and n._attr_dict.get("__aux__")]

    def attr_dict(self):
        """{node_name: {attr: str(value)}} over the whole graph
        (reference: Symbol.attr_dict; what Optimizer.sym_info reads for
        __lr_mult__/__wd_mult__)."""
        out = {}
        for n in self._topo():
            attrs = {k: str(v)
                     for k, v in _json_safe_attrs(n._attr_dict).items()}
            if attrs:
                out[n.name] = attrs
        return out

    def list_inputs(self):
        return [n.name for n in self._topo()
                if n.op is None and "__scalar__" not in n.attrs
                and "__null__" not in n.attrs]

    def list_outputs(self):
        if self._n_outputs == 1:
            return [f"{self.name}_output"]
        return [f"{self.name}_output{i}" for i in range(self._n_outputs)]

    def optimize_for(self, backend="XLA", **kwargs):
        """Partition this graph for a subgraph backend (reference:
        Symbol.optimize_for over src/operator/subgraph/)."""
        from ..subgraph import partition

        return partition(self, backend)

    def get_internals(self):
        return Group([_as_single(n) for n in self._topo()
                      if n.op is not None])

    def list_nodes(self):
        """JSON-style node dicts (used by visualization)."""
        return json.loads(self.tojson())["nodes"]

    # -- evaluation ------------------------------------------------------------

    def _eval_inputs(self, node, env, cache):
        args = []
        for i in node.inputs:
            v = self._eval_node(i, env, cache)
            if isinstance(v, (tuple, list)):
                v = v[i.out_index]
            args.append(v)
        return args

    def _eval_node(self, node, env, cache):
        # keyed by node NAME: s and s[1] are distinct Symbol objects viewing
        # the same graph node, and must share one op evaluation
        key = node.name
        if key in cache:
            return cache[key]
        if node.op is None:
            if "__scalar__" in node.attrs:
                val = node.attrs["__scalar__"]
            elif node.attrs.get("__null__"):
                val = None  # absent optional tensor slot (e.g. bias)
            elif node.name in env:
                val = env[node.name]
            else:
                raise MXNetError(f"unbound variable {node.name}")
        elif node.op == "_subgraph_exec":
            # partitioned region (subgraph.py): one jitted program
            from ..subgraph import subgraph_exec

            val = subgraph_exec(node, self._eval_inputs(node, env, cache))
        else:
            args = self._eval_inputs(node, env, cache)
            opdef = _registry.get(node.op)
            pos, kw_bound, kwargs = _split_kw_inputs(args, node.attrs)
            kwargs.pop("__aux__", None)
            # same execution-scope injection the ndarray invoke wrapper
            # does: mode from the autograd scope, PRNG from the key scope
            if opdef.mode_dependent and kwargs.get("_is_training") is None:
                from .. import autograd as _ag

                kwargs["_is_training"] = _ag.is_training()
            if opdef.random and kwargs.get("_key") is None:
                from ..random import next_key

                kwargs["_key"] = next_key()
            val = opdef.fn(*pos, **kw_bound, **kwargs)
        cache[key] = val
        return val

    def eval_raw(self, **env):
        """Evaluate on raw jax arrays (jit-able)."""
        out = self._eval_node(self, env, {})
        if isinstance(out, tuple):
            return out[self.out_index]
        return out

    def eval(self, ctx=None, **kwargs):
        """Reference: Symbol.eval — bind variables, return NDArray(s);
        multi-output (Group) evals return a list, one per output."""
        from ..ndarray.ndarray import NDArray, _from_jax

        env = {k: (v._data if isinstance(v, NDArray) else v)
               for k, v in kwargs.items()}
        out = self.eval_raw(**env)
        if isinstance(out, tuple):
            return [_from_jax(o) for o in out]
        return _from_jax(out)

    def infer_shape(self, **kwargs):
        """Shape inference: forward abstract evaluation per node via
        jax.eval_shape (replacing nnvm InferShape), with per-op PARAMETER
        shape rules solving unknown weight/bias shapes from data shapes
        (the FInferShape bidirectionality the layer ops need).

        kwargs: name → shape tuple.  Returns (arg_shapes, out_shapes,
        aux_shapes) in list_arguments order; unsolved args → None."""
        known = {k: tuple(v) for k, v in kwargs.items()}
        shapes = self._infer_all(known)
        args = self.list_arguments()
        out = shapes.get(self.name)
        if out is not None and not isinstance(out, list):
            out = [out]
        return ([known.get(a) for a in args],
                [tuple(o) for o in out] if out is not None else None, [])

    infer_shape_partial = infer_shape

    def _infer_all(self, known):
        """Walk topo order; solve unknown input-var shapes via
        _PARAM_SHAPE_RULES; compute node output shapes abstractly."""
        import jax
        import jax.numpy as jnp

        shapes = {}

        def shape_of(sym):
            s = shapes.get(sym.name)
            if isinstance(s, list):
                return s[sym.out_index]
            return s

        for node in self._topo():
            if node.op is None:
                if "__scalar__" in node.attrs:
                    shapes[node.name] = ()
                else:
                    shapes[node.name] = known.get(node.name)
                continue
            in_shapes = [shape_of(i) for i in node.inputs]
            if any(s is None for s in in_shapes):
                rule = _PARAM_SHAPE_RULES.get(node.op)
                if rule is not None:
                    solved = rule(in_shapes, node.attrs)
                    for i, s in zip(node.inputs, solved):
                        if s is not None and shapes.get(i.name) is None:
                            shapes[i.name] = tuple(s)
                            known[i.name] = tuple(s)
                    in_shapes = [shape_of(i) for i in node.inputs]
            if any(s is None for s in in_shapes):
                shapes[node.name] = None
                continue
            specs = [jax.ShapeDtypeStruct(tuple(s), jnp.float32)
                     for s in in_shapes]
            opdef = _registry.get(node.op)
            try:
                def _call(*a, _f=opdef.fn, _attrs=node.attrs):
                    pos, kw_bound, kw = _split_kw_inputs(a, _attrs)
                    kw.pop("__aux__", None)
                    return _f(*pos, **kw_bound, **kw)

                out = jax.eval_shape(_call, *specs)
            except Exception:
                shapes[node.name] = None
                continue
            if isinstance(out, (tuple, list)):
                shapes[node.name] = [tuple(o.shape) for o in out]
            else:
                shapes[node.name] = tuple(out.shape)
        return shapes

    def infer_type(self, **kwargs):
        args = self.list_arguments()
        import numpy as np

        return ([np.float32] * len(args), [np.float32], [])

    # -- serialization ---------------------------------------------------------

    def tojson(self):
        """Reference-layout graph JSON ({'nodes', 'arg_nodes', 'heads'},
        Symbol.tojson)."""
        order = self._topo()
        index = {id(n): i for i, n in enumerate(order)}
        nodes = []
        arg_nodes = []
        for i, n in enumerate(order):
            if n.op is None:
                arg_nodes.append(i)
            entry = {
                "op": "null" if n.op is None else n.op,
                "name": n.name,
                "attrs": {k: json.dumps(v) if not isinstance(v, str) else v
                          for k, v in n.attrs.items()},
                "inputs": [[index[id(s)], s.out_index, 0]
                           for s in n.inputs],
            }
            # node-level user attrs (AttrScope / var(lr_mult=...)):
            # the reference serializes these in the node's "attrs" dict;
            # only plain scalar values qualify — subgraph bookkeeping
            # (Symbol lists, jit caches) and init objects stay
            # runtime-only.  Variables have no op kwargs, so merging
            # into "attrs" is collision-free AND upstream-readable; op
            # nodes keep user attrs under "node_attrs" (merging would
            # corrupt their op kwargs on reload)
            user = _json_safe_attrs(n._attr_dict)
            if user:
                if n.op is None:
                    entry["attrs"] = {**{k: str(v)
                                         for k, v in user.items()},
                                      **entry["attrs"]}
                else:
                    entry["node_attrs"] = user
            nodes.append(entry)
        heads = [[index[id(self)], self.out_index, 0]]
        return json.dumps({"nodes": nodes, "arg_nodes": arg_nodes,
                           "node_row_ptr": list(range(len(nodes) + 1)),
                           "heads": heads,
                           "attrs": {"mxnet_version": ["str", "2.0-tpu"]}},
                          indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- binding (Executor) ----------------------------------------------------

    def simple_bind(self, ctx=None, grad_req="write", **kwargs):
        from .executor import Executor

        arg_shapes, _, _ = self.infer_shape(**kwargs)
        import jax.numpy as jnp

        from ..ndarray.ndarray import _from_jax

        names = self.list_arguments()
        for name, shape in zip(names, arg_shapes):
            if shape is None:
                raise MXNetError(
                    f"simple_bind could not infer the shape of '{name}'; "
                    "pass it explicitly (e.g. "
                    f"simple_bind({name}=(...), ...))")
        args = {name: _from_jax(jnp.zeros(shape, jnp.float32))
                for name, shape in zip(names, arg_shapes)}
        return Executor(self, args, grad_req=grad_req, ctx=ctx)

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from .executor import Executor

        if isinstance(args, (list, tuple)):
            args = dict(zip(self.list_arguments(), args))
        return Executor(self, args, args_grad=args_grad, grad_req=grad_req,
                        aux_states=aux_states, ctx=ctx)


class Group(Symbol):
    """Multiple outputs grouped (reference: mx.sym.Group)."""

    def __init__(self, symbols):
        name = _auto_name("group")
        super().__init__("_group", name, list(symbols), {},
                         n_outputs=len(symbols))

    def eval_raw(self, **env):
        return tuple(s.eval_raw(**env) for s in self.inputs)

    def list_outputs(self):
        return [o for s in self.inputs for o in s.list_outputs()]


def _as_single(node):
    return node


# -- parameter shape rules (the FInferShape bidirectionality; reference:
# per-op FInferShape in src/operator/**) --------------------------------------

def _prod(xs):
    p = 1
    for x in xs:
        p *= x
    return p


def _fc_rule(in_shapes, attrs):
    data = in_shapes[0]
    nh = attrs.get("num_hidden")
    if data is None or nh is None:
        return [None] * len(in_shapes)
    flatten = attrs.get("flatten", True)
    in_units = _prod(data[1:]) if flatten else data[-1]
    out = [data, (nh, in_units)]
    if len(in_shapes) > 2:
        out.append((nh,))
    return out


def _conv_rule(in_shapes, attrs):
    data = in_shapes[0]
    nf = attrs.get("num_filter")
    kernel = attrs.get("kernel")
    if data is None or nf is None or kernel is None:
        return [None] * len(in_shapes)
    groups = attrs.get("num_group", 1)
    k = (kernel,) * (len(data) - 2) if isinstance(kernel, int) \
        else tuple(kernel)
    out = [data, (nf, data[1] // groups) + k]
    if len(in_shapes) > 2:
        out.append((nf,))
    return out


def _deconv_rule(in_shapes, attrs):
    data = in_shapes[0]
    nf = attrs.get("num_filter")
    kernel = attrs.get("kernel")
    if data is None or nf is None or kernel is None:
        return [None] * len(in_shapes)
    groups = attrs.get("num_group", 1)
    k = (kernel,) * (len(data) - 2) if isinstance(kernel, int) \
        else tuple(kernel)
    out = [data, (data[1], nf // groups) + k]
    if len(in_shapes) > 2:
        out.append((nf,))
    return out


def _channel_rule(axis_default):
    def rule(in_shapes, attrs):
        data = in_shapes[0]
        if data is None:
            return [None] * len(in_shapes)
        axis = attrs.get("axis", axis_default)
        c = data[axis]
        return [data] + [(c,)] * (len(in_shapes) - 1)
    return rule


def _embedding_rule(in_shapes, attrs):
    din = attrs.get("input_dim")
    dout = attrs.get("output_dim")
    if din is None or dout is None:
        return [None] * len(in_shapes)
    return [in_shapes[0], (din, dout)]


def _label_like_batch_rule(in_shapes, attrs):
    data = in_shapes[0]
    if data is None:
        return [None] * len(in_shapes)
    return [data, (data[0],)]


_PARAM_SHAPE_RULES = {
    "FullyConnected": _fc_rule,
    "fully_connected": _fc_rule,
    "Convolution": _conv_rule,
    "convolution": _conv_rule,
    "Deconvolution": _deconv_rule,
    "BatchNorm": _channel_rule(1),
    "batch_norm": _channel_rule(1),
    "LayerNorm": _channel_rule(-1),
    "layer_norm": _channel_rule(-1),
    "InstanceNorm": _channel_rule(1),
    "GroupNorm": _channel_rule(1),
    "Embedding": _embedding_rule,
    "SoftmaxOutput": _label_like_batch_rule,
    "softmax_output": _label_like_batch_rule,
}


def var(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
        dtype=None, init=None, stype=None, **kwargs):
    """mx.sym.Variable (reference: symbol.var — extra kwargs must be
    ``__dunder__`` attrs, stored as node attrs; anything else raises,
    matching the reference's variable())."""
    for k in kwargs:
        if not (k.startswith("__") and k.endswith("__")):
            raise ValueError(
                f"Variable attribute {k!r} must start and end with "
                f"double underscores (reference convention: __{k}__)")
    s = Symbol(None, name, [], {})
    scope = _current_attrs()
    if scope:
        s._set_attr(**scope)
    s._set_attr(shape=shape, lr_mult=lr_mult, wd_mult=wd_mult,
                dtype=dtype, init=init, **(attr or {}), **kwargs)
    return s


Variable = var


def _scalar_sym(value):
    value = value if isinstance(value, (int, bool)) else float(value)
    s = var(_auto_name("scalar"))
    s._set_attr(__scalar__=value)
    s.attrs["__scalar__"] = value
    return s


def _encode_index(index):
    """NDArray-style index → JSON-able spec (decoded by ops._sym_index)."""
    items = index if isinstance(index, tuple) else (index,)
    spec = []
    for it in items:
        if isinstance(it, int):
            spec.append(["i", it])
        elif isinstance(it, slice):
            parts = []
            for b in (it.start, it.stop, it.step):
                if b is None:
                    parts.append(None)
                elif isinstance(b, (int,)) or (
                        hasattr(b, "__index__")
                        and not isinstance(b, Symbol)):
                    parts.append(int(b))
                else:
                    raise MXNetError(
                        "Symbol slice bounds must be static ints "
                        f"(got {type(b).__name__}); dynamic bounds "
                        "need slice_axis with a concrete end")
            spec.append(["s"] + parts)
        elif it is Ellipsis:
            spec.append(["e"])
        elif it is None:
            spec.append(["n"])
        else:
            raise MXNetError(
                f"Symbol indexing supports ints/slices/Ellipsis/None, "
                f"got {type(it).__name__}")
    return spec


def _split_kw_inputs(args, attrs):
    """Undo apply_op's kwarg lifting: (positional args, kw-bound tensor
    args, remaining attrs)."""
    attrs = dict(attrs)
    kw_names = attrs.pop("__kw_inputs__", None)
    if kw_names:
        n = len(kw_names)
        return list(args[:-n]), dict(zip(kw_names, args[-n:])), attrs
    return list(args), {}, attrs


def _null_sym():
    s = var(_auto_name("null"))
    s._set_attr(__null__=True)
    s.attrs["__null__"] = True
    return s


def apply_op(opname, *sym_inputs, name=None, **kwargs):
    """Create a graph node applying a registered op."""
    _registry.get(opname)  # validate now
    nm = name or _auto_name(opname.lower().replace("_", ""))
    inputs = list(sym_inputs)
    # absent optional tensor args (e.g. bias with use_bias=False) arrive
    # as trailing Nones from layer code — drop them; the op fn's own
    # defaults apply at eval.  Interior Nones would misalign positions.
    while inputs and inputs[-1] is None:
        inputs.pop()
    # interior Nones (an absent bias BETWEEN tensor args) become null
    # placeholder variables that evaluate to None, keeping positions
    inputs = [_null_sym() if i is None else i for i in inputs]
    # positional python scalars (e.g. clip(x, 0, 6) in relu6) become
    # scalar-constant variables so positions stay aligned at eval
    inputs = [i if isinstance(i, Symbol) else _scalar_sym(i)
              for i in inputs]
    # Symbol-valued KWARGS (e.g. multi_head_attention(qkv_weight=w))
    # are tensor inputs, not attributes: lift them to the inputs list
    # and record their names so eval rebinds them (__kw_inputs__ is a
    # plain string list — JSON round-trips through symbol.json)
    kw_syms = [(k, v) for k, v in kwargs.items() if isinstance(v, Symbol)]
    if kw_syms:
        for k, _ in kw_syms:
            kwargs.pop(k)
        kwargs["__kw_inputs__"] = [k for k, _ in kw_syms]
        inputs += [v for _, v in kw_syms]
    # multi-output ops: reflected lazily when indexing
    out = Symbol(opname, nm, inputs, kwargs)
    scope = _current_attrs()
    if scope:
        out._set_attr(**scope)
    return out


def load(fname):
    """Load a symbol.json (reference: mx.sym.load)."""
    with open(fname) as f:
        data = json.load(f)
    return fromjson(data)


_INTERNAL_ATTRS = {"__aux__", "__null__", "__scalar__", "__kw_inputs__"}


def _json_safe_attrs(attr_dict):
    """USER node attrs only: plain scalar values, minus the internal
    markers and subgraph/runtime bookkeeping (Symbol lists, init
    objects, jit caches — anything non-primitive)."""
    return {k: v for k, v in attr_dict.items()
            if k not in _INTERNAL_ATTRS
            and isinstance(v, (str, int, float, bool))}


def fromjson(data):
    from ..attribute import _LOCAL as _attr_local

    if isinstance(data, str):
        data = json.loads(data)
    nodes = data["nodes"]
    built = []
    # deserialization must NOT stamp an ambient AttrScope onto loaded
    # nodes (the reference JSON loader bypasses AttrScope): suspend it
    saved_scope, _attr_local.stack = _attr_local.stack, []
    try:
        for nd in nodes:
            attrs = {}
            for k, v in nd.get("attrs", {}).items():
                try:
                    attrs[k] = json.loads(v)
                except (json.JSONDecodeError, TypeError):
                    attrs[k] = v
            if nd["op"] == "null":
                v = var(nd["name"])
                # restore variable-level attrs (__scalar__ values,
                # __aux__ markers) so save/load round-trips evaluation
                # semantics
                v.attrs.update(attrs)
                if attrs.get("__aux__"):
                    v._set_attr(__aux__=True)
                # user attrs (lr_mult/__lr_mult__/ctx_group...) live in
                # the variable's "attrs" dict in the reference format —
                # surface them in _attr_dict so attr_dict()/sym_info
                # sees them on upstream-exported files too
                user = _json_safe_attrs(attrs)
                if user:
                    v._set_attr(**user)
                built.append(v)
            elif nd["op"] == "_group":
                # rebuild as a real Group: keeps multi-output count and
                # the specialized per-output eval
                built.append(Group(
                    [built[i][oi] if oi else built[i]
                     for i, oi, _ in nd["inputs"]]))
            else:
                inputs = [built[i][oi] for i, oi, _ in nd["inputs"]]
                sym = apply_op(nd["op"], *inputs, name=nd["name"],
                               **attrs)
                built.append(sym)
            if nd.get("node_attrs"):
                built[-1]._set_attr(**nd["node_attrs"])
    finally:
        _attr_local.stack = saved_scope
    head, oi, _ = data["heads"][0]
    return built[head][oi] if oi else built[head]


def trace_block(block, inputs=None):
    """Build a Symbol graph from a hybridized gluon block by running its
    hybrid_forward with ``F = mx.sym`` and Variable inputs — the
    reference's dual-dispatch export path (python/mxnet/gluon/block.py
    HybridBlock._build_cache builds the nnvm graph the same way).

    Tracing happens in predict mode: the deploy format is an inference
    graph (BatchNorm normalizes with global stats, Dropout is identity),
    matching the reference's exported symbol.json semantics.
    """
    from .. import autograd as _ag

    shapes = getattr(block, "_last_input_shapes", None) or []
    if inputs is None:
        names = ["data"] if len(shapes) <= 1 else [
            f"data{i}" for i in range(len(shapes))]
        inputs = [var(n, shape=s)
                  for n, s in zip(names, shapes)] or [var("data")]
    elif isinstance(inputs, str):
        inputs = [var(inputs, shape=shapes[0] if shapes else None)]
    elif all(isinstance(i, str) for i in inputs):
        inputs = [var(n, shape=s) for n, s in zip(
            inputs, list(shapes) + [None] * len(inputs))]
    with _ag.predict_mode(), _ag.pause():
        out = block(*inputs)
    if isinstance(out, (list, tuple)):
        return Group(list(out))
    if not isinstance(out, Symbol):
        raise MXNetError(
            f"trace_block: block {block} returned {type(out).__name__}, "
            "not a Symbol — its forward() bypasses hybrid_forward (pure "
            "imperative Block); export requires a HybridBlock")
    return out
