"""Graph executor.

Reference parity: src/executor/graph_executor.cc + python/mxnet/executor.py
— Executor with arg_arrays/grad_arrays/aux_states, forward/backward,
outputs, copy_params_from.

TPU-first: "binding" jit-compiles the whole graph once per shape signature
(forward AND backward as single XLA programs) — the reference's
InferShape→PlanMemory→AttachOpExecs pipeline is the XLA compiler.
"""

from __future__ import annotations

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _from_jax


class Executor:
    def __init__(self, symbol, args, args_grad=None, grad_req="write",
                 aux_states=None, ctx=None):
        self._symbol = symbol
        self._arg_names = symbol.list_arguments()
        self.arg_dict = dict(args) if args else {}
        for name in self._arg_names:
            if name not in self.arg_dict:
                raise MXNetError(f"missing argument {name} in bind")
        self.arg_arrays = [self.arg_dict[n] for n in self._arg_names]
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(self._arg_names, grad_req))
        self._grad_req = grad_req
        if args_grad is None:
            import jax.numpy as jnp

            args_grad = {n: _from_jax(jnp.zeros_like(self.arg_dict[n]._data))
                         for n in self._arg_names
                         if grad_req.get(n, "null") != "null"}
        elif isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(self._arg_names, args_grad))
        self.grad_dict = args_grad
        self.grad_arrays = [self.grad_dict.get(n)
                            for n in self._arg_names]
        self.aux_dict = dict(aux_states) if aux_states else {}
        self.aux_arrays = list(self.aux_dict.values())
        self.outputs = []
        self._fwd_jit = None
        self._grad_jit = None

    def _build(self):
        import jax

        sym = self._symbol
        names = self._arg_names
        grad_names = [n for n in names
                      if self._grad_req.get(n, "null") != "null"]
        g_idx = [names.index(n) for n in grad_names]

        def fwd(vals):
            env = dict(zip(names, vals))
            return sym.eval_raw(**env)

        self._fwd_jit = jax.jit(fwd)

        def loss_like(vals, out_ct):
            out = fwd(vals)
            if isinstance(out, (tuple, list)):
                return sum((o * c).sum() for o, c in zip(out, out_ct))
            return (out * out_ct).sum()

        self._grad_jit = jax.jit(jax.grad(loss_like))
        self._g_idx = g_idx

    def forward(self, is_train=False, **kwargs):
        """Reference: Executor.forward — optionally update args from
        kwargs, run the compiled graph."""
        from .. import autograd as _ag

        for k, v in kwargs.items():
            if k in self.arg_dict:
                self.arg_dict[k]._set_data(
                    v._data if isinstance(v, NDArray) else v)
        if self._fwd_jit is None:
            self._build()
        vals = [self.arg_dict[n]._data for n in self._arg_names]
        self._last_is_train = bool(is_train)  # Monitor re-evals in-mode
        with (_ag.train_mode() if is_train else _ag.predict_mode()):
            out = self._fwd_jit(vals)
        outs = out if isinstance(out, (tuple, list)) else [out]
        self.outputs = [_from_jax(o) for o in outs]
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        """Reference: Executor.backward — grads into grad_arrays honoring
        grad_req write/add."""
        import jax.numpy as jnp

        if self._grad_jit is None:
            self._build()
        if not self.outputs:
            raise MXNetError("call forward before backward")
        if out_grads is None:
            out_ct = [jnp.ones_like(o._data) for o in self.outputs]
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            out_ct = [g._data if isinstance(g, NDArray) else jnp.asarray(g)
                      for g in out_grads]
        vals = [self.arg_dict[n]._data for n in self._arg_names]
        grads = self._grad_jit(vals, tuple(out_ct)
                               if len(out_ct) > 1 else out_ct[0])
        for n, g in zip(self._arg_names, grads):
            req = self._grad_req.get(n, "null")
            if req == "null" or self.grad_dict.get(n) is None:
                continue
            tgt = self.grad_dict[n]
            if req == "add":
                tgt._set_data(tgt._data + g)
            else:
                tgt._set_data(g)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, array in arg_params.items():
            if name in self.arg_dict:
                self.arg_dict[name]._set_data(array._data)
            elif not allow_extra_params:
                raise ValueError(f"Found name '{name}' that is not in the "
                                 "arguments")
        if aux_params:
            for name, array in aux_params.items():
                if name in self.aux_dict:
                    self.aux_dict[name]._set_data(array._data)
                elif not allow_extra_params:
                    raise ValueError(f"Found name '{name}' that is not in "
                                     "auxiliary states")

    def reshape(self, partial_shaping=False, allow_up_sizing=False,
                **kwargs):
        """Rebind with new shapes (XLA recompiles per signature anyway)."""
        import jax.numpy as jnp

        new_args = {}
        for n in self._arg_names:
            shape = kwargs.get(n, self.arg_dict[n].shape)
            new_args[n] = _from_jax(jnp.zeros(shape, jnp.float32))
        return Executor(self._symbol, new_args, grad_req=self._grad_req)
