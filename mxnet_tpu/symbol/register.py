"""Generated symbol op wrappers (reference: python/mxnet/symbol/register.py
— import-time codegen over the op registry, mirroring the ndarray side)."""

from __future__ import annotations

from ..ops import registry as _registry
from .symbol import Symbol, apply_op


def _make_wrapper(opname):
    def wrapper(*args, name=None, **kwargs):
        sym_args = [a for a in args if isinstance(a, Symbol)]
        non_sym = [a for a in args if not isinstance(a, Symbol)]
        if non_sym and not sym_args:
            raise TypeError(
                f"symbol op {opname} expects Symbol inputs; for arrays use "
                f"mx.nd.{opname}")
        return apply_op(opname, *args, name=name, **kwargs)

    wrapper.__name__ = opname
    wrapper.__doc__ = f"(symbol wrapper for op '{opname}')"
    return wrapper


def populate(namespace):
    for opname in _registry.all_ops():
        if opname not in namespace:
            namespace[opname] = _make_wrapper(opname)
