"""Symbolic API (reference: python/mxnet/symbol/)."""

from .symbol import (Group, Symbol, Variable, apply_op, fromjson, load,
                     trace_block, var)
from .executor import Executor
from . import register as _register

_register.populate(globals())
