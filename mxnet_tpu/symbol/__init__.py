"""Symbolic API (reference: python/mxnet/symbol/)."""

from .symbol import (Group, Symbol, Variable, apply_op, fromjson, load,
                     trace_block, var)
from .executor import Executor
from . import register as _register
from . import contrib

_register.populate(globals())
