"""mx.sym.contrib — symbolic contrib namespace (reference:
python/mxnet/symbol/contrib.py).  Mirrors the contrib op surface into
Symbol graph builders: every registered `_contrib_*` / `contrib_*` op
gets a wrapper creating graph nodes, same dual-dispatch convention as
the main symbol namespace."""

from __future__ import annotations

from ..ops import registry as _registry
from .symbol import apply_op


def _make_wrapper(opname):
    def wrapper(*args, name=None, **kwargs):
        return apply_op(opname, *args, name=name, **kwargs)

    wrapper.__name__ = opname
    wrapper.__doc__ = f"(symbol contrib wrapper for op '{opname}')"
    return wrapper


def _expose(ns):
    for name in _registry.all_ops():
        if name.startswith("_contrib_"):
            ns.setdefault(name[len("_contrib_"):], _make_wrapper(name))
        elif name.startswith("contrib_"):
            ns.setdefault(name[len("contrib_"):], _make_wrapper(name))


_expose(globals())
