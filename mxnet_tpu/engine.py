"""Execution-engine shim.

Reference parity: src/engine/ (ThreadedEnginePerDevice / NaiveEngine,
Engine::WaitForAll, async exception propagation re-thrown at WaitToRead).

TPU-first design: XLA/PJRT dispatch is already asynchronous with dataflow
ordering, so there is no hand-built dependency engine.  What remains here is
the *policy* surface the reference exposes:

- ``MXNET_ENGINE_TYPE=NaiveEngine`` → every op blocks until complete
  (bisecting async bugs, reference: src/engine/naive_engine.cc);
- ``wait_all()`` → drain all in-flight device work
  (reference: Engine::WaitForAll);
- deferred errors: JAX raises device errors at block time, matching the
  reference's re-throw-at-WaitToRead semantics (tests/python/unittest/
  test_exc_handling.py is mirrored by tests/test_engine.py).
"""

from __future__ import annotations

import os

_NAIVE = os.environ.get("MXNET_ENGINE_TYPE", "").lower() == "naiveengine"


def is_naive() -> bool:
    return _NAIVE


def set_engine_type(name: str) -> None:
    """'NaiveEngine' → synchronous; anything else → async (default)."""
    global _NAIVE
    _NAIVE = name.lower() == "naiveengine"


_COMPILE_CACHE_DIR = None


def ensure_compile_cache() -> str | None:
    """Point JAX's persistent compilation cache at
    ``MXTPU_COMPILE_CACHE_DIR`` (idempotent; returns the directory, or
    None when the env var is unset).

    The whole-step capture (`gluon.captured`) compiles ONE large XLA
    program per training configuration; on a restart after preemption
    the retrace is unavoidable but the XLA compile — the expensive half
    — need not be.  With the cache dir set, a restarted worker's
    first-step latency drops to trace + cache-deserialize (bench.py's
    ``restart_first_step_ms`` measures exactly this).  Thresholds are
    zeroed so even small programs (the eager oracle's per-group
    updates) persist.
    """
    global _COMPILE_CACHE_DIR
    cache_dir = os.environ.get("MXTPU_COMPILE_CACHE_DIR")
    if not cache_dir:
        return None
    if _COMPILE_CACHE_DIR == cache_dir:
        return cache_dir
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except (AttributeError, ValueError):
        pass  # older jax: defaults still persist the big programs
    try:
        # enable for all backends (by default jax only persists for
        # TPU/GPU; the CPU-fallback bench path wants it too)
        jax.config.update("jax_persistent_cache_enable_xla_caches",
                          "all")
    except (AttributeError, ValueError):
        pass
    try:
        # the cache module latches its enabled/dir decision at the FIRST
        # compile; anything already compiled (e.g. parameter init ops
        # before the Trainer existed) froze it — reset so the next
        # compile re-reads the config and starts persisting
        from jax.experimental.compilation_cache import (
            compilation_cache as _cc)
        _cc.reset_cache()
    except Exception:
        pass  # cache is best-effort; compilation still works without
    _COMPILE_CACHE_DIR = cache_dir
    return cache_dir


def maybe_sync(arr):
    """Block on an array if NaiveEngine mode is on. Returns the array."""
    if _NAIVE and hasattr(arr, "block_until_ready"):
        arr.block_until_ready()
    return arr


def wait_all() -> None:
    """Block until all asynchronously dispatched work has completed."""
    import jax

    # PJRT exposes no global barrier; syncing every live array is the
    # equivalent drain.  jax.live_arrays() covers everything dispatched.
    # Donated buffers (the fused trainer step's inputs) stay in the live
    # list until GC but cannot be blocked on — skip them.
    for a in jax.live_arrays():
        try:
            if a.is_deleted():
                continue
            a.block_until_ready()
        except RuntimeError:
            continue   # deleted between the check and the block


def bulk(size: int | None = None):
    """Reference compat: engine bulking (MXNET_EXEC_BULK_EXEC_*).

    XLA fuses within a jit region, so bulking is a no-op context manager kept
    for API compatibility with mx.engine.bulk.
    """
    import contextlib

    return contextlib.nullcontext()


_BULK_SIZE = 15  # reference default engine bulking window


def set_bulk_size(size):
    """Reference: mx.engine.set_bulk_size (MXEngineSetBulkSize) — sets
    the async-engine op-bulking window and returns the previous value.
    Under XLA the whole jitted step IS one bulk (CachedOp compiles the
    full graph), so the knob has nothing to tune: accepted for API
    compatibility, returns the previous (nominal) value."""
    global _BULK_SIZE
    prev = _BULK_SIZE
    _BULK_SIZE = int(size)
    return prev
