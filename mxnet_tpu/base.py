"""Base utilities: errors, registries, dtype plumbing.

Reference parity: python/mxnet/base.py (error handling, registry helpers) and
3rdparty/dmlc-core's parameter/registry machinery.  There is no FFI boundary
here — the "C API" of the reference (src/c_api/) collapses into direct Python
calls because the compute core is XLA; the native runtime pieces live in
``mxnet_tpu/_native`` (C++) and are loaded lazily via ctypes where present.
"""

from __future__ import annotations

import os
import threading
import numpy as _np


class MXNetError(RuntimeError):
    """Framework error type (reference: MXGetLastError / dmlc::Error)."""


_GLOBAL_REGISTRIES: dict[str, dict] = {}


def registry(kind: str) -> dict:
    """Get (creating if needed) a named global registry dict."""
    return _GLOBAL_REGISTRIES.setdefault(kind, {})


class _Registry:
    """A tiny name->object registry with decorator-style registration.

    Mirrors dmlc::Registry / mx.registry.get_register_func: case-insensitive
    lookup, alias support.
    """

    def __init__(self, kind: str):
        self.kind = kind
        self._store: dict[str, object] = {}

    def register(self, obj=None, name: str | None = None, aliases: tuple = ()):
        def _do(o):
            key = (name or getattr(o, "__name__", None) or str(o)).lower()
            self._store[key] = o
            for a in aliases:
                self._store[a.lower()] = o
            return o

        if obj is None:
            return _do
        return _do(obj)

    def get(self, name: str):
        key = str(name).lower()
        if key not in self._store:
            raise MXNetError(
                f"{self.kind} '{name}' is not registered. "
                f"Known: {sorted(self._store)}"
            )
        return self._store[key]

    def __contains__(self, name):
        return str(name).lower() in self._store

    def keys(self):
        return self._store.keys()


# dtype handling ---------------------------------------------------------------

_DTYPE_ALIASES = {
    "float32": "float32", "float64": "float64", "float16": "float16",
    "bfloat16": "bfloat16", "uint8": "uint8", "int8": "int8",
    "int32": "int32", "int64": "int64", "bool": "bool",
}


def np_dtype(dtype):
    """Normalize a dtype spec to a numpy/jax dtype object."""
    import jax.numpy as jnp

    if dtype is None:
        return jnp.float32
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            return jnp.bfloat16
        return _np.dtype(dtype)
    return dtype


def x64_scope(cond):
    """Context manager enabling jax x64 when `cond` — the x32 default
    otherwise silently truncates int64/float64 values and drops scatter
    updates on >2^31 dims (INT64_TENSOR_SIZE honesty; see
    tests/test_ndarray.py round-trips)."""
    import contextlib

    if cond:
        import jax

        return jax.enable_x64(True)
    return contextlib.nullcontext()


def is_64bit_dtype(dtype):
    try:
        return dtype is not None and dtype != "bfloat16" \
            and _np.dtype(dtype).itemsize == 8 \
            and _np.dtype(dtype).kind in "iuf"
    except TypeError:
        return False


def x64_scope_if(dtype):
    """x64_scope keyed on a dtype being 64-bit."""
    return x64_scope(is_64bit_dtype(dtype))


def getenv_int(name: str, default: int) -> int:
    """Env config plane (reference: dmlc::GetEnv, docs/faq/env_var.md)."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def getenv_bool(name: str, default: bool) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


class _ThreadLocalStack(threading.local):
    """with-scope stacks (contexts, autograd state, name scopes)."""

    def __init__(self):
        self.stack = []

    def push(self, v):
        self.stack.append(v)

    def pop(self):
        return self.stack.pop()

    def top(self, default=None):
        return self.stack[-1] if self.stack else default
