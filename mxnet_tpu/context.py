"""Device contexts: ``mx.cpu()``, ``mx.tpu(i)``, ``mx.gpu(i)``.

Reference parity: python/mxnet/context.py (Context class, with-scope device
stack, ``current_context()``).  TPU-first change: a Context resolves to a JAX
device; ``gpu`` is kept as an alias for the accelerator so reference scripts
(`ctx=mx.gpu(0)`) run unmodified on TPU.
"""

from __future__ import annotations

import functools

from .base import MXNetError, _ThreadLocalStack


@functools.lru_cache(maxsize=None)
def _jax_devices(platform: str | None = None):
    """Devices a Context may resolve to: LOCAL (addressable) only.  In a
    multi-process run jax.devices() spans all hosts; ctx cpu(0)/tpu(0)
    must mean THIS process's device 0 (reference: device ids are
    process-local)."""
    import jax

    try:
        devs = tuple(jax.devices(platform)) if platform \
            else tuple(jax.devices())
        local = tuple(d for d in devs
                      if d.process_index == jax.process_index())
        return local or devs
    except RuntimeError:
        return ()


def _accelerator_platform() -> str | None:
    """Return the non-CPU platform name if one is present (tpu preferred)."""
    import jax

    platforms = {d.platform for d in jax.devices()}
    for p in ("tpu", "axon", "gpu", "cuda", "rocm"):
        if p in platforms:
            return p
    return None


class Context:
    """A device context. devtype in {'cpu', 'tpu', 'gpu'}.

    ``gpu`` is an accelerator alias: on a TPU machine ``mx.gpu(0)`` is the
    first TPU chip, so reference training scripts port without edits.
    """

    devtype2mask = {"cpu": 1, "gpu": 2, "tpu": 2, "cpu_pinned": 3}
    _stack = _ThreadLocalStack()

    def __init__(self, device_type: str, device_id: int = 0):
        if device_type not in ("cpu", "gpu", "tpu", "cpu_pinned"):
            raise MXNetError(f"unknown device type {device_type!r}")
        self.device_type = "cpu" if device_type == "cpu_pinned" else device_type
        self.device_id = int(device_id)

    # -- resolution to a JAX device -------------------------------------------
    @property
    def jax_device(self):
        if self.device_type == "cpu":
            devs = _jax_devices("cpu")
        else:
            plat = _accelerator_platform()
            devs = _jax_devices(plat) if plat else ()
            if not devs:  # no accelerator: fall back to CPU transparently
                devs = _jax_devices("cpu")
        if not devs:
            raise MXNetError(f"no JAX device for context {self}")
        return devs[self.device_id % len(devs)]

    # -- identity -------------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    __str__ = __repr__

    # -- with-scope -----------------------------------------------------------
    def __enter__(self):
        Context._stack.push(self)
        return self

    def __exit__(self, *exc):
        Context._stack.pop()

    @classmethod
    def default_ctx(cls):
        return cls._stack.top(default=Context("cpu", 0))


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Accelerator alias (reference scripts use mx.gpu); maps to TPU here."""
    return Context("gpu", device_id)


def current_context() -> Context:
    return Context.default_ctx()


def num_gpus() -> int:
    plat = _accelerator_platform()
    return len(_jax_devices(plat)) if plat else 0


def num_tpus() -> int:
    return num_gpus()
