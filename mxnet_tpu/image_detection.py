"""Detection image pipeline (reference: python/mxnet/image/detection.py —
DetAugmenter family + ImageDetIter, the SSD/RCNN training data path).

Host-side numpy preprocessing like mx.image: labels are the reference's
packed format  [header_width, object_width, (header extras...),
obj0(class, xmin, ymin, xmax, ymax, extras...), obj1...]  with
coordinates normalized to [0, 1]; batches pad the object dimension with
-1 rows (invalid), exactly what MultiBoxTarget expects.
"""

from __future__ import annotations

import json

import numpy as _np

from .base import MXNetError
from .image import (BrightnessJitterAug, CastAug, ColorNormalizeAug,
                    ContrastJitterAug, ForceResizeAug, HueJitterAug,
                    ImageIter, LightingAug, RandomGrayAug, ResizeAug,
                    SaturationJitterAug, SequentialAug, _to_np)


class DetAugmenter:
    """Detection augmenter: __call__(src, label) -> (src, label)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(),
                           self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only Augmenter; label passes through (reference:
    DetBorrowAug)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.__class__.__name__)
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly pick one augmenter (or none with skip_prob)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if _np.random.rand() < self.skip_prob or not self.aug_list:
            return src, label
        aug = self.aug_list[_np.random.randint(len(self.aug_list))]
        return aug(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image and box x-coordinates with probability p."""

    def __init__(self, p=0.5):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _np.random.rand() < self.p:
            arr = _to_np(src)[:, ::-1]
            label = label.copy()
            tmp = 1.0 - label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = tmp
            return arr, label
        return _to_np(src), label


def _box_iou_coverage(crop, boxes):
    """Fraction of each box's area inside `crop` (x0, y0, x1, y1)."""
    ix0 = _np.maximum(boxes[:, 1], crop[0])
    iy0 = _np.maximum(boxes[:, 2], crop[1])
    ix1 = _np.minimum(boxes[:, 3], crop[2])
    iy1 = _np.minimum(boxes[:, 4], crop[3])
    iw = _np.maximum(ix1 - ix0, 0)
    ih = _np.maximum(iy1 - iy0, 0)
    inter = iw * ih
    area = _np.maximum((boxes[:, 3] - boxes[:, 1])
                       * (boxes[:, 4] - boxes[:, 2]), 1e-12)
    return inter / area


class DetRandomCropAug(DetAugmenter):
    """Constrained random crop (reference: DetRandomCropAug): sample a
    crop whose min-object-coverage constraint holds; boxes are clipped
    and re-normalized, under-covered objects ejected."""

    def __init__(self, min_object_covered=0.1,
                 aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range,
                         min_eject_coverage=min_eject_coverage,
                         max_attempts=max_attempts)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _sample_crop(self, label):
        for _ in range(self.max_attempts):
            area = _np.random.uniform(*self.area_range)
            ratio = _np.random.uniform(*self.aspect_ratio_range)
            w = min(_np.sqrt(area * ratio), 1.0)
            h = min(_np.sqrt(area / ratio), 1.0)
            x0 = _np.random.uniform(0, 1 - w)
            y0 = _np.random.uniform(0, 1 - h)
            crop = (x0, y0, x0 + w, y0 + h)
            if label.size == 0:
                return crop, None
            cov = _box_iou_coverage(crop, label)
            # reference semantics: EVERY object intersecting the crop
            # must be covered >= min_object_covered (amin over
            # intersecting boxes) — a crop may exclude an object
            # entirely, but not truncate one below the constraint
            inter = cov > 0
            if inter.any() and cov[inter].min() >= self.min_object_covered:
                return crop, cov
        return None, None

    def __call__(self, src, label):
        arr = _to_np(src)
        crop, cov = self._sample_crop(label)
        if crop is None:
            return arr, label
        x0, y0, x1, y1 = crop
        hgt, wid = arr.shape[:2]
        px0, py0 = int(x0 * wid), int(y0 * hgt)
        px1, py1 = max(int(x1 * wid), px0 + 1), max(int(y1 * hgt),
                                                    py0 + 1)
        out = arr[py0:py1, px0:px1]
        if label.size == 0:
            return out, label
        keep = cov >= self.min_eject_coverage
        new = label[keep].copy()
        w, h = x1 - x0, y1 - y0
        new[:, 1] = _np.clip((new[:, 1] - x0) / w, 0, 1)
        new[:, 2] = _np.clip((new[:, 2] - y0) / h, 0, 1)
        new[:, 3] = _np.clip((new[:, 3] - x0) / w, 0, 1)
        new[:, 4] = _np.clip((new[:, 4] - y0) / h, 0, 1)
        return out, new


class DetRandomPadAug(DetAugmenter):
    """Random expansion pad (reference: DetRandomPadAug): place the
    image inside a larger canvas, rescale boxes."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__(aspect_ratio_range=aspect_ratio_range,
                         area_range=area_range, max_attempts=max_attempts,
                         pad_val=pad_val)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        arr = _to_np(src)
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            area = _np.random.uniform(*self.area_range)
            ratio = _np.random.uniform(*self.aspect_ratio_range)
            nw = _np.sqrt(area * ratio)
            nh = _np.sqrt(area / ratio)
            if nw < 1 or nh < 1:
                continue
            pw, ph = int(w * nw), int(h * nh)
            x0 = _np.random.randint(0, pw - w + 1)
            y0 = _np.random.randint(0, ph - h + 1)
            canvas = _np.empty((ph, pw, arr.shape[2]), arr.dtype)
            canvas[...] = _np.asarray(self.pad_val, arr.dtype)
            canvas[y0:y0 + h, x0:x0 + w] = arr
            if label.size:
                label = label.copy()
                label[:, 1] = (label[:, 1] * w + x0) / pw
                label[:, 2] = (label[:, 2] * h + y0) / ph
                label[:, 3] = (label[:, 3] * w + x0) / pw
                label[:, 4] = (label[:, 4] * h + y0) / ph
            return canvas, label
        return arr, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None,
                       std=None, brightness=0, contrast=0, saturation=0,
                       pca_noise=0, hue=0, inter_method=2,
                       min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Reference: mx.image.CreateDetAugmenter — the SSD default
    pipeline."""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(area_range[1], 1.0)),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(area_range[0], 1.0), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(
        ForceResizeAug((data_shape[2], data_shape[1]), inter_method)))
    jitters = []
    if brightness:
        jitters.append(BrightnessJitterAug(brightness))
    if contrast:
        jitters.append(ContrastJitterAug(contrast))
    if saturation:
        jitters.append(SaturationJitterAug(saturation))
    if hue:
        jitters.append(HueJitterAug(hue))
    if jitters:
        auglist.append(DetBorrowAug(SequentialAug(jitters)))
    if pca_noise > 0:
        eigval = _np.array([55.46, 4.794, 1.148])
        eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                            [-0.5808, -0.0045, -0.8140],
                            [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(
            LightingAug(pca_noise, eigval, eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(RandomGrayAug(rand_gray)))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator (reference: mx.image.ImageDetIter): labels are
    variable-object packed rows; batches emit (B, max_objects,
    object_width) with -1 padding."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, label_name="label", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape=(3,)
                                          + tuple(data_shape)[1:])
        self._label_name = label_name
        super().__init__(batch_size, data_shape, label_width=1,
                         path_imgrec=path_imgrec,
                         path_imglist=path_imglist, path_root=path_root,
                         shuffle=shuffle, aug_list=aug_list, **kwargs)
        # scan labels once for (max_objects, object_width)
        self._obj_width = None
        max_obj = 1
        for idx, (kind, item) in enumerate(self._items):
            lab = self._raw_label(kind, item)
            objs = self._parse_label(lab)
            max_obj = max(max_obj, objs.shape[0])
            if objs.size:
                if self._obj_width is None:
                    self._obj_width = objs.shape[1]
                elif objs.shape[1] != self._obj_width:
                    raise MXNetError(
                        f"ImageDetIter: record {idx} has object width "
                        f"{objs.shape[1]} but the dataset started with "
                        f"{self._obj_width} — mixed widths cannot batch")
        self._obj_width = self._obj_width or 5
        self._max_obj = max_obj

    def _raw_label(self, kind, item):
        from . import recordio as rio

        if kind == "rec":
            header, _ = rio.unpack(item)
            return _np.asarray(header.label, _np.float32)
        return _np.asarray(item[1], _np.float32)

    @staticmethod
    def _parse_label(raw):
        """Packed [hw, ow, (hw-2 extras), obj...] -> (N, ow) array."""
        raw = _np.asarray(raw, _np.float32).ravel()
        if raw.size < 2:
            raise MXNetError("ImageDetIter: label too short for the "
                             "packed detection format")
        hw = int(raw[0])
        ow = int(raw[1])
        if hw < 2 or hw > raw.size:
            raise MXNetError(
                f"ImageDetIter: header width {hw} invalid for a "
                f"{raw.size}-value packed label (must be in [2, size])")
        if ow < 5:
            raise MXNetError(f"ImageDetIter: object width {ow} < 5")
        body = raw[hw:]
        if body.size % ow:
            raise MXNetError(
                f"ImageDetIter: label body of {body.size} values is not "
                f"a multiple of object width {ow} (malformed packed "
                "label)")
        return body.reshape(-1, ow)

    @property
    def provide_label(self):
        from .io import DataDesc

        return [DataDesc(self._label_name,
                         (self.batch_size, self._max_obj,
                          self._obj_width))]

    def next(self):
        from .io import DataBatch
        from . import recordio as rio
        from .image import imdecode_np, imread, _to_np as to_np
        from .ndarray.ndarray import _from_jax

        if self.cur + self.batch_size > len(self._items):
            raise StopIteration
        c, h, w = self.data_shape
        data = _np.empty((self.batch_size, c, h, w), _np.float32)
        label = _np.full((self.batch_size, self._max_obj,
                          self._obj_width), -1.0, _np.float32)
        for i in range(self.batch_size):
            kind, item = self._items[self._order[self.cur + i]]
            if kind == "rec":
                header, payload = rio.unpack(item)
                img = imdecode_np(payload)
                lab = _np.asarray(header.label, _np.float32)
            else:
                path, lab = item
                img = to_np(imread(path))
                lab = _np.asarray(lab, _np.float32)
            objs = self._parse_label(lab)
            for aug in self.auglist:
                img, objs = aug(img, objs)
            arr = to_np(img).astype(_np.float32)
            data[i] = arr.transpose(2, 0, 1)
            n = min(objs.shape[0], self._max_obj)
            if n:
                label[i, :n] = objs[:n]
        self.cur += self.batch_size
        import jax.numpy as jnp

        return DataBatch(data=[_from_jax(jnp.asarray(data))],
                         label=[_from_jax(jnp.asarray(label))], pad=0)
