"""Gluon Trainer.

Reference parity: python/mxnet/gluon/trainer.py — Trainer(params, optimizer,
optimizer_params, kvstore, update_on_kvstore), step/allreduce_grads/update,
learning-rate control, optimizer-state save/load.

TPU-first: with one logical array per parameter, `allreduce_grads` is the
cross-process reduce (kvstore dist types → ICI/DCN all-reduce); the
single-chip path applies fused optimizer ops directly.  For whole-step
compilation (grad + reduce + update in ONE XLA program) see
mxnet_tpu.parallel.DataParallelTrainer, this class's jit-native sibling.
"""

from __future__ import annotations

from .. import optimizer as opt
from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {
            "kvstore": kvstore, "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = []
        self._contains_sparse_weight = False

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]
        # multi-tensor path: shares each Updater's state dict, so
        # save/load_states round-trip regardless of which path stepped
        self._grouped_updaters = [opt.GroupedUpdater(u)
                                  for u in self._updaters]

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        kv = None
        if kvstore:
            from .. import kvstore as kvs

            kv = kvs.create(kvstore) if isinstance(kvstore, str) else kvstore
            if kv.num_workers == 1 and not kvstore_requires_store(kv):
                kv = None  # single worker: local fused update path
        if kv is not None:
            if update_on_kvstore is None:
                update_on_kvstore = True
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
            for i, param in enumerate(self._params):
                if param._grad_req != "null":
                    kv.init(i, param.data())
        self._kvstore = kv
        self._update_on_kvstore = bool(update_on_kvstore) and kv is not None
        self._kv_initialized = True

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its "
                              "learning rate can be accessed.")
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its "
                              "learning rate is mutated.")
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce_grads + update (reference: Trainer.step)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise AssertionError(
                "allreduce_grads() when parameters are updated on kvstore "
                "is not supported. Try setting `update_on_kvstore` to False "
                "when creating trainer.")
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None:
            return
        if self._update_on_kvstore:
            for i, param in enumerate(self._params):
                if param._grad_req != "null":
                    # push grad; pull updated weight (server-side optimizer)
                    self._kvstore.push(i, param.list_grad(), priority=-i)
            return
        keys = [i for i, param in enumerate(self._params)
                if param._grad_req != "null"]
        if opt.grouped.fused_step_enabled() \
                and hasattr(self._kvstore, "bucketed_pushpull"):
            grads = [self._params[i].list_grad() for i in keys]
            self._kvstore.bucketed_pushpull(keys, grads, outs=grads)
            return
        for i in keys:
            self._kvstore.pushpull(i, self._params[i].list_grad(),
                                   out=self._params[i].list_grad(),
                                   priority=-i)

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        assert not self._update_on_kvstore, \
            "update() when parameters are updated on kvstore is not " \
            "supported. Try setting `update_on_kvstore` to False when " \
            "creating trainer."
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        updates = []
        for i, param in enumerate(self._params):
            if param._grad_req == "null":
                continue
            if not ignore_stale_grad:
                data = param.data()
                if hasattr(data, "_fresh_grad") and not data._fresh_grad:
                    raise UserWarning(
                        f"Gradient of Parameter `{param.name}` on context "
                        "has not been updated by backward since last step.")
            if self._update_on_kvstore:
                self._kvstore.pull(i, param.list_data(), priority=-i)
            else:
                updates.append((i, param.grad(), param.data()))
        if not updates:
            return
        indices, grads, weights = map(list, zip(*updates))
        if opt.grouped.fused_step_enabled():
            # one jitted dispatch per (kernel, hyper-params, dtype) group
            self._grouped_updaters[0](indices, grads, weights)
        else:
            for i, g, w in updates:
                self._updaters[0](i, g, w)

    def save_states(self, fname):
        """Save optimizer/updater states (reference: Trainer.save_states)."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(
                    dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            self._updaters[0].set_states(states)
            self._updaters[0].optimizer = self._optimizer
            self._validate_updater_states(fname)
        self._optimizer.param_dict = {
            i: param for i, param in enumerate(self._params)}

    def _validate_updater_states(self, fname):
        """Loaded states are keyed by parameter INDEX; if the param list
        changed (count or shapes) since save, applying them would silently
        step the wrong arrays — fail loudly instead."""

        def _leaves(state):
            if state is None:
                return []
            if isinstance(state, (list, tuple)):
                return [a for s in state for a in _leaves(s)]
            return [state] if isinstance(state, NDArray) else []

        states = self._updaters[0].states
        nparams = len(self._params)
        for idx, state in states.items():
            if not isinstance(idx, int) or idx < 0 or idx >= nparams:
                raise MXNetError(
                    f"Trainer.load_states: '{fname}' holds optimizer state "
                    f"for parameter index {idx!r}, but this trainer has "
                    f"only {nparams} parameters. The parameter list "
                    "changed since the states were saved.")
            param = self._params[idx]
            pshape = tuple(param.shape) if param.shape else None
            for arr in _leaves(state):
                if pshape is not None and tuple(arr.shape) != pshape:
                    raise MXNetError(
                        f"Trainer.load_states: state shape "
                        f"{tuple(arr.shape)} for parameter index {idx} "
                        f"('{param.name}') does not match the parameter "
                        f"shape {pshape}. The parameter list changed "
                        "since the states were saved.")


def kvstore_requires_store(kv):
    """dist types always go through the store (cross-process reduce)."""
    return kv.type.startswith("dist")
