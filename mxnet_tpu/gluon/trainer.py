"""Gluon Trainer.

Reference parity: python/mxnet/gluon/trainer.py — Trainer(params, optimizer,
optimizer_params, kvstore, update_on_kvstore), step/allreduce_grads/update,
learning-rate control, optimizer-state save/load.

TPU-first: with one logical array per parameter, `allreduce_grads` is the
cross-process reduce (kvstore dist types → ICI/DCN all-reduce); the
single-chip path applies fused optimizer ops directly.  For whole-step
compilation (grad + reduce + update in ONE XLA program) see
mxnet_tpu.parallel.DataParallelTrainer, this class's jit-native sibling.
"""

from __future__ import annotations

import logging

from .. import numerics
from .. import optimizer as opt
from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict

_LOG = logging.getLogger("mxnet_tpu.gluon.trainer")

_MAX_SKIP_RECORDS = 1000


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None,
                 update_on_kvstore=None, clip_global_norm=None):
        from .. import engine, obs
        engine.ensure_compile_cache()  # MXTPU_COMPILE_CACHE_DIR, if set
        obs.ensure_from_env()          # MXTPU_METRICS_PORT, if set
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        self._param2idx = {}
        for i, param in enumerate(params):
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._param2idx[param.name] = i
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_params = {
            "kvstore": kvstore, "update_on_kvstore": update_on_kvstore}
        self._kv_initialized = False
        self._kvstore = None
        self._update_on_kvstore = None
        self._params_to_init = []
        self._contains_sparse_weight = False
        # numerical-health guard (mxnet_tpu/numerics.py): clip_global_norm
        # falls back to MXTPU_CLIP_GLOBAL_NORM when not given; skipped
        # steps are recorded here (bounded deque-style list)
        self._clip_global_norm = None if clip_global_norm is None \
            else float(clip_global_norm)
        self.divergence_monitor = None
        self.skipped_steps = []
        self._step_count = 0
        # resumable input pipeline (gluon/data/state.py): when attached,
        # each guarded step tags the divergence monitor with the batch
        # that fed it, so a rollback can quarantine the poisoned batch
        self._data_pipeline = None
        # integrity plane (mxnet_tpu/integrity.py): attach_integrity
        # makes the captured step fingerprint the state every
        # plane.every steps and attest it against the gang
        self._integrity_plane = None

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an " \
                "Optimizer instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]
        # multi-tensor path: shares each Updater's state dict, so
        # save/load_states round-trip regardless of which path stepped
        self._grouped_updaters = [opt.GroupedUpdater(u)
                                  for u in self._updaters]

    def _init_kvstore(self):
        config = self._kvstore_params
        kvstore = config["kvstore"]
        update_on_kvstore = config["update_on_kvstore"]
        kv = None
        if kvstore:
            from .. import kvstore as kvs

            kv = kvs.create(kvstore) if isinstance(kvstore, str) else kvstore
            if kv.num_workers == 1 and not kvstore_requires_store(kv):
                kv = None  # single worker: local fused update path
        if kv is not None:
            if update_on_kvstore is None:
                update_on_kvstore = True
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
            for i, param in enumerate(self._params):
                if param._grad_req != "null":
                    kv.init(i, param.data())
        self._kvstore = kv
        self._update_on_kvstore = bool(update_on_kvstore) and kv is not None
        self._kv_initialized = True

    @property
    def learning_rate(self):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its "
                              "learning rate can be accessed.")
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    def set_learning_rate(self, lr):
        if not isinstance(self._optimizer, opt.Optimizer):
            raise UserWarning("Optimizer has to be defined before its "
                              "learning rate is mutated.")
        self._optimizer.set_learning_rate(lr)

    def _clip_norm(self):
        return self._clip_global_norm \
            if self._clip_global_norm is not None \
            else numerics.clip_global_norm_env()

    def _set_rescale(self, batch_size):
        # amp: fold the loss-scaler's unscale into rescale_grad, so the
        # division happens inside the fused step instead of a separate
        # pass over the gradients (DynamicLossScaler.unscale returns new
        # arrays and is only needed on manual paths)
        scaler = getattr(self, "_amp_loss_scaler", None)
        if scaler is not None:
            self._scale = 1.0 / scaler.loss_scale
        self._optimizer.rescale_grad = self._scale / batch_size

    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce_grads + update (reference: Trainer.step)."""
        from .. import telemetry

        # no-op (returns None) when train_step already opened the record
        acc = telemetry.step_begin(path="manual")
        n_skipped = len(self.skipped_steps)
        try:
            if not self._kv_initialized:
                self._init_kvstore()
            self._set_rescale(batch_size)
            health = self._allreduce_grads()
            self._update(ignore_stale_grad, health=health)
        except BaseException:
            telemetry.step_abort(acc)
            raise
        telemetry.step_end(acc, step=self._step_count,
                           skipped=len(self.skipped_steps) > n_skipped)

    def train_step(self, block, loss_fn, data, label=None, batch_size=None,
                   grad_accum=1, ignore_stale_grad=False):
        """One full training step — forward, loss, backward, gradient
        accumulation, health guard, clip, optimizer update — returning
        the (per-microbatch, when ``grad_accum > 1``) loss.

        When the configuration is capturable (hybridized block, fused
        optimizer, local reduce — see `gluon.captured`), the entire
        step runs as ONE donated jit program with a single host
        readback after the update; otherwise (or under
        ``MXTPU_CAPTURED_STEP=0``) it runs the eager multi-dispatch
        path, which doubles as the captured path's bitwise oracle.

        The captured path never touches the parameters' gradient
        buffers — gradients live only inside the program — so
        ``ignore_stale_grad`` only applies to the eager fallback, and
        manual ``backward()`` + ``step()`` flows should not be
        interleaved with ``train_step`` on the same trainer step.
        """
        from .. import resilience
        from .. import telemetry
        from . import captured as _captured

        if not self._kv_initialized:
            self._init_kvstore()
        if batch_size is None:
            batch_size = data.shape[0]
        # autotune consult (MXTPU_AUTOTUNE=replay|search|off): replay a
        # stored winner or search the knob space ONCE per capture
        # signature, before this step's capture lookup sees the knobs
        from .. import autotune as _autotune

        k = _autotune.maybe_tune(self, block, loss_fn, data, label,
                                 int(grad_accum))
        self._maybe_shard_batch(data, label)
        acc = telemetry.step_begin()
        n_skipped = len(self.skipped_steps)
        step = None
        try:
            # a pending nan_grad / bit_flip_grad injection needs a
            # materialized gradient buffer to land in: route that step
            # to the eager oracle
            if _captured.captured_step_enabled() \
                    and not resilience.fault_armed("nan_grad") \
                    and not resilience.fault_armed("bit_flip_grad"):
                hits0 = _captured.cache_stats()["hits"] if acc else 0
                step = _captured.get_step(self, block, loss_fn, data,
                                          label, k)
                if step is not None and acc is not None:
                    telemetry.note_path("captured")
                    telemetry.note(
                        cache_hit=_captured.cache_stats()["hits"] > hits0)
            if step is not None:
                result = step(self, data, label, batch_size)
                if acc is not None:
                    telemetry.note(flops=step.cost_flops())
                    peak = step.memory_high_water()
                    if peak is not None:
                        telemetry.note(device_peak_bytes=peak)
                    coll = step.collective_bytes_by_axis()
                    if coll:
                        telemetry.note(collective_bytes_by_axis=coll)
                    pstats = step.pipeline_stats()
                    if pstats is not None:
                        telemetry.note(
                            bubble_fraction=pstats["bubble_fraction"])
            else:
                self._note_sparse_fallback(block, loss_fn, data, k)
                result = self._eager_train_step(
                    block, loss_fn, data, label, batch_size, k,
                    ignore_stale_grad)
        except BaseException:
            telemetry.step_abort(acc)
            raise
        telemetry.step_end(acc, step=self._step_count,
                           skipped=len(self.skipped_steps) > n_skipped)
        return result

    def _note_sparse_fallback(self, block, loss_fn, data, grad_accum):
        """A sparse_grad=True model landing on the eager oracle is a
        performance cliff (multi-dispatch, host-side coalesce) the user
        explicitly tried to avoid — emit a ``sparse_fallback{reason}``
        telemetry event rather than degrading silently.  Dense models
        fall back silently as before."""
        if not any(p._grad_req != "null"
                   and getattr(p, "_grad_stype", None) == "row_sparse"
                   for p in self._params):
            return
        from .. import resilience
        from .. import telemetry
        from . import captured as _captured
        if not _captured.captured_step_enabled():
            reason = "captured step disabled (MXTPU_CAPTURED_STEP=0)"
        elif resilience.fault_armed("nan_grad") \
                or resilience.fault_armed("bit_flip_grad"):
            reason = "pending gradient fault injection"
        else:
            reason = getattr(self, "_sparse_fallback_reason", None)
            self._sparse_fallback_reason = None
            if reason is None:
                reason = _captured.ineligible_reason(
                    self, block, loss_fn, data, grad_accum) \
                    or "capture declined"
        telemetry.event("sparse_fallback", reason=reason)

    def _maybe_shard_batch(self, data, label):
        """When the parameters are committed over a multi-device mesh
        (`parallel.shard_model`), place the batch over its dp axis
        IN-PLACE, before the captured/eager branch — both paths must
        see the identical committed placement or the eager oracle's
        programs would lay data out differently and break bitwise
        parity with the captured program."""
        from ..ndarray import NDArray
        from ..parallel.sharding import batch_sharding, mesh_of_params

        mesh = mesh_of_params(self._params)
        if mesh is None:
            return
        import jax

        for nd in (data, label):
            if isinstance(nd, NDArray) and nd.ndim >= 1:
                sh = batch_sharding(mesh, nd.shape[0])
                nd._set_data(jax.device_put(nd._data, sh))

    def _eager_train_step(self, block, loss_fn, data, label, batch_size,
                          grad_accum, ignore_stale_grad):
        """The multi-dispatch step the captured program is checked
        against: per-microbatch forward/backward with grad buffers,
        then the regular guarded `step`."""
        from .. import autograd as ag

        scaler = getattr(self, "_amp_loss_scaler", None)
        k = grad_accum
        if k == 1:
            with ag.record():
                out = block(data)
                loss = loss_fn(out, label) if label is not None \
                    else loss_fn(out)
                scaled = loss * scaler.loss_scale \
                    if scaler is not None else loss
            scaled.backward()
            result = loss
        else:
            if data.shape[0] % k:
                raise ValueError(
                    f"batch size {data.shape[0]} is not divisible by "
                    f"grad_accum {k}")
            m = data.shape[0] // k
            params = [p for p in self._params if p._grad_req != "null"]
            losses = []
            with ag.accumulate_grads(params):
                for j in range(k):
                    xs = data[j * m:(j + 1) * m]
                    ys = None if label is None \
                        else label[j * m:(j + 1) * m]
                    with ag.record():
                        out = block(xs)
                        loss = loss_fn(out, ys) if ys is not None \
                            else loss_fn(out)
                        scaled = loss * scaler.loss_scale \
                            if scaler is not None else loss
                    scaled.backward()
                    losses.append(loss)
            import jax.numpy as jnp

            from ..ndarray import _from_jax

            result = _from_jax(jnp.stack([l._data for l in losses]))
        self.step(batch_size, ignore_stale_grad)
        return result

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            raise AssertionError(
                "allreduce_grads() when parameters are updated on kvstore "
                "is not supported. Try setting `update_on_kvstore` to False "
                "when creating trainer.")
        self._allreduce_grads()

    def _allreduce_grads(self):
        """Cross-process gradient reduce.  Returns the fused ``(2,)``
        health array when `bucketed_pushpull` computed it post-reduce
        (avoiding a second pass over the gradients), else None — the
        guarded `_update` then runs its own health reduction."""
        if self._kvstore is None:
            return None
        if self._update_on_kvstore:
            for i, param in enumerate(self._params):
                if param._grad_req != "null":
                    # push grad; pull updated weight (server-side optimizer)
                    self._kvstore.push(i, param.list_grad(), priority=-i)
            return None
        keys = [i for i, param in enumerate(self._params)
                if param._grad_req != "null"]
        from ..parallel.sharding import mesh_of_params

        if mesh_of_params(self._params) is not None:
            # GSPMD owns the collectives when params live on a mesh:
            # the bucketed host-side pushpull would flat-concat the
            # grads, silently all-gathering every shard — per-key
            # pushpull keeps each reduce shard-shaped
            for i in keys:
                self._kvstore.pushpull(i, self._params[i].list_grad(),
                                       out=self._params[i].list_grad(),
                                       priority=-i)
            return None
        if opt.grouped.fused_step_enabled() \
                and hasattr(self._kvstore, "bucketed_pushpull"):
            grads = [self._params[i].list_grad() for i in keys]
            bp = self._kvstore.bucketed_pushpull
            want = numerics.grad_guard_enabled() \
                or self._clip_norm() is not None
            code = getattr(getattr(bp, "__func__", bp), "__code__", None)
            if want and code is not None and "health" in \
                    code.co_varnames[:code.co_argcount
                                     + code.co_kwonlyargcount]:
                return bp(keys, grads, outs=grads, health=True)
            bp(keys, grads, outs=grads)
            return None
        for i in keys:
            self._kvstore.pushpull(i, self._params[i].list_grad(),
                                   out=self._params[i].list_grad(),
                                   priority=-i)
        return None

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        assert not self._update_on_kvstore, \
            "update() when parameters are updated on kvstore is not " \
            "supported. Try setting `update_on_kvstore` to False when " \
            "creating trainer."
        self._set_rescale(batch_size)
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False, health=None):
        updates = []
        for i, param in enumerate(self._params):
            if param._grad_req == "null":
                continue
            if not ignore_stale_grad:
                data = param.data()
                if hasattr(data, "_fresh_grad") and not data._fresh_grad:
                    raise UserWarning(
                        f"Gradient of Parameter `{param.name}` on context "
                        "has not been updated by backward since last step.")
            if self._update_on_kvstore:
                self._kvstore.pull(i, param.list_data(), priority=-i)
            else:
                updates.append((i, param.grad(), param.data()))
        self._step_count += 1
        if not updates:
            return
        indices, grads, weights = map(list, zip(*updates))
        fused = opt.grouped.fused_step_enabled()
        guard_on = numerics.grad_guard_enabled()
        clip = self._clip_norm()
        if fused and (guard_on or clip is not None):
            # nan_grad / bit_flip_grad fault sites; a fired injection
            # invalidates any health computed during the allreduce
            from .. import integrity as _integrity

            flipped = _integrity.maybe_bit_flip_grad(grads=grads)
            if numerics.maybe_inject_nan_grad(grads) or flipped \
                    or health is None:
                health = numerics.grad_health(
                    [g._data if isinstance(g, NDArray) else g
                     for g in grads])
            guard = numerics.StepGuard(health, skip=guard_on, clip=clip)
            snapshot = self._snapshot_update_counts(indices) \
                if guard_on else None
            self._grouped_updaters[0](indices, grads, weights, guard=guard)
            self._finalize_guarded_step(guard, snapshot)
        elif fused:
            # one jitted dispatch per (kernel, hyper-params, dtype) group
            self._grouped_updaters[0](indices, grads, weights)
        else:
            for i, g, w in updates:
                self._updaters[0](i, g, w)

    def attach_data_pipeline(self, pipeline):
        """Attach a resumable input pipeline (a ``DataLoader`` built
        with ``seed=``, or a ``DevicePrefetcher`` wrapping one).  The
        guarded step then (a) passes the just-delivered batch id to the
        divergence monitor — a rollback quarantines the streak's
        batches so replay skips them — and (b) notes ``samples_seen``
        on each step's telemetry record.  Also wired into an attached
        ``divergence_monitor`` so its rollback rewinds the pipeline to
        the restored checkpoint's sample offset.  Returns self."""
        self._data_pipeline = pipeline
        if self.divergence_monitor is not None:
            self.divergence_monitor.data_pipeline = pipeline
        return self

    def _batch_ids(self):
        """[(epoch, batch_idx)] of the last-delivered batch, or None."""
        p = self._data_pipeline
        if p is None:
            return None
        bid = p.last_batch_id()
        return None if bid is None else [bid]

    # -- integrity plane plumbing (mxnet_tpu/integrity.py) ---------------------

    def attach_integrity(self, plane):
        """Attach an `integrity.IntegrityPlane`: with MXTPU_INTEGRITY
        on, the captured step fingerprints the parameter+optimizer
        state every ``plane.every`` steps (in-program, read back with
        the StepGuard's single sync) and attests it against the
        plane's peers.  Returns self for chaining."""
        self._integrity_plane = plane
        return self

    def _integrity_due(self):
        """Does the step ABOUT to dispatch attest?  Read pre-dispatch
        (the traced ``attest`` predicate of the captured program)."""
        plane = self._integrity_plane
        return plane is not None and plane.due(self._step_count + 1)

    def _integrity_attest(self, fp):
        """One attestation round for the step that just committed."""
        plane = self._integrity_plane
        if plane is None or fp is None:
            return None
        return plane.attest(self._step_count, fp)

    # -- numerical-health guard plumbing (mxnet_tpu/numerics.py) ---------------

    def _snapshot_update_counts(self, indices):
        """Host-side optimizer step counters, captured BEFORE the guarded
        update bumps them — a skipped step must leave Adam's
        bias-correction `t` (and friends) exactly as if the bad batch
        never existed."""
        o = self._optimizer
        return (o.num_update,
                {i: o._index_update_count.get(i) for i in indices})

    def _restore_update_counts(self, snapshot):
        o = self._optimizer
        num_update, per_index = snapshot
        o.num_update = num_update
        for i, v in per_index.items():
            if v is None:
                o._index_update_count.pop(i, None)
            else:
                o._index_update_count[i] = v

    def _finalize_guarded_step(self, guard, snapshot):
        """The step's ONE host readback happens here, AFTER the update
        dispatch, so XLA pipelines the guard with the step.  On an
        unhealthy step the fused programs already returned the donated
        weights/states unchanged; this rolls back the host-side step
        counters, halves the amp loss scale and emits a StepSkipped."""
        from .. import telemetry

        scaler = getattr(self, "_amp_loss_scaler", None)
        monitor = self.divergence_monitor
        if not guard.skip:
            # clipping-only: no host decision needed unless a monitor or
            # scaler wants the scalars
            if monitor is not None:
                monitor.observe(step=self._step_count,
                                grad_norm=guard.grad_norm, healthy=True,
                                batch_indices=self._batch_ids())
            self._note_guard_scalars(guard, scaler)
            self._integrity_attest(guard.fingerprint)
            return
        healthy = guard.healthy
        if not healthy:
            self._restore_update_counts(snapshot)
            rec = numerics.StepSkipped(
                step=self._step_count, reason="non-finite gradients",
                grad_norm=guard.grad_norm,
                loss_scale=scaler.loss_scale if scaler else None)
            self.skipped_steps.append(rec)
            del self.skipped_steps[:-_MAX_SKIP_RECORDS]
            _LOG.warning("skipped optimizer step: %r", rec)
            telemetry.count("step.skipped")
            telemetry.event("step_skipped", step=rec.step,
                            reason=rec.reason, grad_norm=rec.grad_norm,
                            loss_scale=rec.loss_scale)
        if scaler is not None:
            scaler.update_scale(not healthy)
            self._scale = 1.0 / scaler.loss_scale
        if monitor is not None:
            monitor.observe(step=self._step_count,
                            grad_norm=guard.grad_norm, healthy=healthy,
                            batch_indices=self._batch_ids())
        self._note_guard_scalars(guard, scaler)
        self._integrity_attest(guard.fingerprint)

    def _note_guard_scalars(self, guard, scaler):
        """Attach guard scalars to the open StepStats record — only via
        `StepGuard.peek()`, so telemetry never adds a host readback the
        step didn't already pay for."""
        from .. import telemetry

        host = guard.peek()
        if host is not None:
            import math as _math
            _, sq = host
            telemetry.note(grad_norm=_math.sqrt(sq) if sq >= 0.0
                           else float("nan"))
        if scaler is not None:
            telemetry.note(loss_scale=scaler.loss_scale)
        if self._data_pipeline is not None:
            telemetry.note(samples_seen=int(
                self._data_pipeline.samples_seen))

    def save_states(self, fname):
        """Save optimizer/updater states (reference: Trainer.save_states)."""
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as fout:
                fout.write(self._updaters[0].get_states(
                    dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                states = f.read()
            self._updaters[0].set_states(states)
            self._updaters[0].optimizer = self._optimizer
            self._validate_updater_states(fname)
        self._optimizer.param_dict = {
            i: param for i, param in enumerate(self._params)}

    def _validate_updater_states(self, fname):
        """Loaded states are keyed by parameter INDEX; if the param list
        changed (count or shapes) since save, applying them would silently
        step the wrong arrays — fail loudly instead."""

        def _leaves(state):
            if state is None:
                return []
            if isinstance(state, (list, tuple)):
                return [a for s in state for a in _leaves(s)]
            return [state] if isinstance(state, NDArray) else []

        states = self._updaters[0].states
        nparams = len(self._params)
        for idx, state in states.items():
            if not isinstance(idx, int) or idx < 0 or idx >= nparams:
                raise MXNetError(
                    f"Trainer.load_states: '{fname}' holds optimizer state "
                    f"for parameter index {idx!r}, but this trainer has "
                    f"only {nparams} parameters. The parameter list "
                    "changed since the states were saved.")
            param = self._params[idx]
            pshape = tuple(param.shape) if param.shape else None
            for arr in _leaves(state):
                if pshape is not None and tuple(arr.shape) != pshape:
                    raise MXNetError(
                        f"Trainer.load_states: state shape "
                        f"{tuple(arr.shape)} for parameter index {idx} "
                        f"('{param.name}') does not match the parameter "
                        f"shape {pshape}. The parameter list changed "
                        "since the states were saved.")


def kvstore_requires_store(kv):
    """dist types always go through the store (cross-process reduce)."""
    return kv.type.startswith("dist")
