"""Gluon utilities.

Reference parity: python/mxnet/gluon/utils.py — split_data / split_and_load
(the data-parallel primitive), clip_global_norm, check_sha1, download.

TPU-first note: ``split_and_load`` with a list of contexts keeps the
reference API for per-device slices, but the idiomatic multi-chip path is a
*sharded* batch — pass ``even_split='shard'`` sentinel or use
``mxnet_tpu.parallel`` to lay the global batch over the mesh data axis and
let XLA move the shards.
"""

from __future__ import annotations

import hashlib
import os

import numpy as _np

from ..base import MXNetError
from ..context import Context, cpu
from ..ndarray.ndarray import NDArray, _from_jax


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split along batch_axis into num_slice slices (reference:
    utils.split_data)."""
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}. Use a batch size "
            f"that's multiple of {num_slice} or set even_split=False to "
            "allow uneven partitioning of data.")
    step = size // num_slice
    if not even_split and size < num_slice:
        step = 1
        num_slice = size
    if batch_axis == 0:
        slices = [data[i * step:(i + 1) * step] if i < num_slice - 1
                  else data[i * step:size] for i in range(num_slice)]
    else:
        from .. import ndarray as nd

        slices = [nd.slice_axis(data, batch_axis, i * step, (i + 1) * step)
                  if i < num_slice - 1
                  else nd.slice_axis(data, batch_axis, i * step, size)
                  for i in range(num_slice)]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split and load slices onto ctx_list (reference:
    utils.split_and_load)."""
    if not isinstance(data, NDArray):
        from .. import ndarray as nd

        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [i.as_in_context(ctx) for i, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so that the 2-norm of the concatenation is at most
    max_norm (reference: utils.clip_global_norm)."""
    def _norm(array):
        if array.stype == "default":
            x = array.reshape((-1,))
            return (x * x).sum()
        return array.norm().square()

    assert len(arrays) > 0, "arrays must not be empty"
    ctx = arrays[0].context
    total_norm = sum(_norm(arr).as_in_context(ctx) for arr in arrays)
    total_norm = total_norm.sqrt()
    if check_isfinite:
        total_norm_val = float(total_norm.asscalar())
        if not _np.isfinite(total_norm_val):
            import warnings

            warnings.warn(UserWarning("nan or inf is detected. Clipping "
                                      "results will be undefined."),
                          stacklevel=2)
    scale = max_norm / (total_norm + 1e-8)
    from .. import ndarray as nd

    scale = nd.minimum(scale, nd.ones_like(scale))
    for arr in arrays:
        arr *= scale
    if check_isfinite:
        return total_norm_val
    return total_norm


def check_sha1(filename, sha1_hash):
    """Check a file against an expected sha1 (reference: utils.check_sha1)."""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):
    """Reference: utils.download.  This build runs with zero egress; only
    file:// URLs and already-present files are supported."""
    if path is None:
        fname = url.split("/")[-1]
        path = fname
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
        path = fname
    else:
        fname = path
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    if url.startswith("file://"):
        import shutil

        shutil.copyfile(url[7:], fname)
        return fname
    raise MXNetError(
        f"download of {url} requires network access, which is unavailable "
        "in this environment. Place the file at {fname} manually.")


def _indent(s_, numSpaces):
    """Indent string (reference: utils._indent)."""
    s = s_.split("\n")
    if len(s) == 1:
        return s_
    first = s.pop(0)
    s = [first] + [(numSpaces * " ") + line for line in s]
    return "\n".join(s)


def shape_is_known(shape):
    """True iff shape is fully known (no 0 dims)."""
    if shape is None:
        return False
    for dim_size in shape:
        if dim_size == 0:
            return False
    return True
