"""Basic neural-network layers.

Reference parity: python/mxnet/gluon/nn/basic_layers.py — Sequential,
HybridSequential, Dense, Dropout, BatchNorm, InstanceNorm, LayerNorm,
GroupNorm, Embedding, Flatten, Lambda, HybridLambda.
"""

from __future__ import annotations

import numpy as _np

from ... import autograd as _ag
from ...base import np_dtype
from ..block import Block, HybridBlock, record_aux_update
from ..parameter import Parameter
from .activations import Activation


class Sequential(Block):
    """Stacks Blocks sequentially (reference: nn.Sequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join([f"  ({key}): " +
                            repr(block).replace("\n", "\n  ")
                            for key, block in self._children.items()])
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings

            warnings.warn(
                f"All children of this Sequential layer '{self.prefix}' are "
                "HybridBlocks. Consider using HybridSequential for the best "
                "performance.", stacklevel=2)
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stacks HybridBlocks; hybridizes to one XLA program (reference:
    nn.HybridSequential)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join([f"  ({key}): " +
                            repr(block).replace("\n", "\n  ")
                            for key, block in self._children.items()])
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __len__(self):
        return len(self._children)

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer y = act(xW^T + b) (reference: nn.Dense;
    op: src/operator/nn/fully_connected.cc).  One MXU matmul."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._flatten = flatten
        with self.name_scope():
            self._units = units
            self._in_units = in_units
            self.weight = self.params.get(
                "weight", shape=(units, in_units),
                init=weight_initializer, dtype=dtype,
                allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), init=bias_initializer,
                    dtype=dtype, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x, *args):
        if self._flatten:
            in_units = int(_np.prod(x.shape[1:]))
        else:
            in_units = x.shape[-1]
        self.weight.shape = (self._units, in_units)

    def hybrid_forward(self, F, x, weight, bias=None):
        act = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units,
                               flatten=self._flatten)
        if self.act is not None:
            act = self.act(act)
        return act

    def __repr__(self):
        shape = self.weight.shape
        return "{name}({layout}, {act})".format(
            name=self.__class__.__name__,
            act=self.act if self.act else "linear",
            layout=f"{shape[0]} -> {shape[1] if shape[1] else None}")


class Dropout(HybridBlock):
    """Dropout (reference: nn.Dropout; op: src/operator/nn/dropout.cc).
    TPU PRNG keys flow through random.key_scope under hybridize."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        if self._rate > 0:
            return F.Dropout(x, p=self._rate, axes=self._axes)
        return F.identity(x)

    def __repr__(self):
        return f"{self.__class__.__name__}(p = {self._rate}, " \
               f"axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with moving-average aux states (reference:
    nn.BatchNorm; op: src/operator/nn/batch_norm.cc).

    The reference mutates moving_mean/moving_var inside the kernel; here the
    update is functionalized through record_aux_update so it works identically
    eagerly and inside the hybridized XLA program.
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        self._momentum = momentum
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        if in_channels != 0:
            self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean,
                  self.running_var):
            p.shape = (channels,)

    def cast(self, dtype):
        if _np.dtype(dtype).name == "float16":
            dtype = "float32"  # reference: BN statistics stay fp32
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        training = _ag.is_training() and not self._use_global_stats
        if not training:
            return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                               output_mean_var=False, _is_training=False,
                               **self._kwargs)
        out, mean, var = F.BatchNorm(
            x, gamma, beta, running_mean, running_var,
            output_mean_var=True, _is_training=True, **self._kwargs)
        m = self._momentum
        new_mean = m * running_mean + (1.0 - m) * mean
        new_var = m * running_var + (1.0 - m) * var
        self._store_aux(self.running_mean, new_mean)
        self._store_aux(self.running_var, new_var)
        return out

    @staticmethod
    def _store_aux(param, value):
        from ...ndarray.ndarray import NDArray

        raw = value._data if isinstance(value, NDArray) else value
        if not record_aux_update(param.name, raw):
            with _ag.pause():
                param.data()._set_data(raw)

    def __repr__(self):
        in_channels = self.gamma.shape[0]
        return "{name}({content}, in_channels={in_channels})".format(
            name=self.__class__.__name__,
            content=", ".join(f"{k}={v}" for k, v in self._kwargs.items()),
            in_channels=in_channels if in_channels else None)


class InstanceNorm(HybridBlock):
    """Instance normalization (reference: nn.InstanceNorm)."""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"eps": epsilon}
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis == 1:
            return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)
        x = x.swapaxes(1, self._axis)
        return F.InstanceNorm(x, gamma, beta,
                              eps=self._epsilon).swapaxes(1, self._axis)


class LayerNorm(HybridBlock):
    """Layer normalization (reference: nn.LayerNorm; op added ≥1.3)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._kwargs = {"eps": epsilon, "axis": axis, "center": center,
                        "scale": scale}
        self._axis = axis
        self._epsilon = epsilon
        self._center = center
        self._scale = scale
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[self._axis]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)


class GroupNorm(HybridBlock):
    """Group normalization (reference: nn.GroupNorm, ≥1.6)."""

    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_groups = num_groups
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x, *args):
        channels = x.shape[1]
        self.gamma.shape = (channels,)
        self.beta.shape = (channels,)

    def hybrid_forward(self, F, x, gamma, beta):
        return F.GroupNorm(x, gamma, beta, num_groups=self._num_groups,
                           eps=self._epsilon)


class Embedding(HybridBlock):
    """Index → vector lookup (reference: nn.Embedding;
    op: src/operator/tensor/indexing_op.cc)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = bool(sparse_grad)
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim),
                init=weight_initializer, dtype=dtype,
                allow_deferred_init=True,
                grad_stype="row_sparse" if sparse_grad else "default")

    def hybrid_forward(self, F, x, weight):
        from ...autograd import is_recording
        from ...ndarray.ndarray import NDArray

        if self._sparse_grad and isinstance(x, NDArray) \
                and isinstance(weight, NDArray) and is_recording():
            # eager tape: compact row-sparse weight gradient (under jit
            # the dense gather's scatter-add transpose is already the
            # fused row update, so the plain path is used there)
            from ...ops.indexing import sparse_embedding

            return sparse_embedding(x, weight)
        return F.Embedding(x, weight, **self._kwargs)

    def __repr__(self):
        return "{block_name}({input_dim} -> {output_dim}, {dtype})".format(
            block_name=self.__class__.__name__,
            input_dim=self._input_dim, output_dim=self._output_dim,
            dtype=self.weight.dtype)


class Flatten(HybridBlock):
    """Flattens to (batch, -1) (reference: nn.Flatten)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.Flatten(x)

    def __repr__(self):
        return self.__class__.__name__


class Lambda(Block):
    """Wraps a function or op name as a Block (reference: nn.Lambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd

            assert hasattr(nd, function), \
                f"Function name {function} is not found in ndarray."
            self._func_impl = getattr(nd, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda: "
                             f"{function} of type {type(function)}")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._func_name})"


class HybridLambda(HybridBlock):
    """Wraps a function or op name as a HybridBlock (reference:
    nn.HybridLambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as nd

            assert hasattr(nd, function), \
                f"Function name {function} is not found in ndarray."
            self._func = lambda F, *args: getattr(F, function)(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda: "
                             f"{function} of type {type(function)}")

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._func_name})"
