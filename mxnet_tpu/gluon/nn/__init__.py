"""Neural network layers (reference: python/mxnet/gluon/nn/)."""

# reference exposes the Block family on gluon.nn too
# (python/mxnet/gluon/nn/__init__.py re-exports ..block)
from ..block import Block, HybridBlock, SymbolBlock
from .activations import (Activation, ELU, GELU, LeakyReLU, PReLU, SELU,
                          Swish)
from .basic_layers import (BatchNorm, Dense, Dropout, Embedding, Flatten,
                           GroupNorm, HybridLambda, HybridSequential,
                           InstanceNorm, Lambda, LayerNorm, Sequential)
from .conv_layers import (AvgPool1D, AvgPool2D, AvgPool3D, Conv1D,
                          Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D,
                          Conv3DTranspose, GlobalAvgPool1D, GlobalAvgPool2D,
                          GlobalAvgPool3D, GlobalMaxPool1D, GlobalMaxPool2D,
                          GlobalMaxPool3D, MaxPool1D, MaxPool2D, MaxPool3D,
                          ReflectionPad2D)
