"""Activation layers (reference: python/mxnet/gluon/nn/activations.py)."""

from __future__ import annotations

from ..block import HybridBlock


class Activation(HybridBlock):
    """Applies a named activation (reference: nn.Activation)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha, **kwargs):
        assert alpha >= 0, "Slope coefficient for LeakyReLU must be no less " \
            "than 0."
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._alpha})"


class PReLU(HybridBlock):
    """Parametric leaky ReLU with learned slope (reference: nn.PReLU)."""

    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer

        if alpha_initializer is None:
            alpha_initializer = initializer.Constant(0.25)
        with self.name_scope():
            self.alpha = self.params.get("alpha", shape=(1,),
                                         init=alpha_initializer)

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, gamma=alpha, act_type="prelu")


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type="gelu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)
