"""Estimator: the high-level gluon fit API (reference:
python/mxnet/gluon/contrib/estimator — train/val loop with event
handlers)."""

from __future__ import annotations

import time

from ... import autograd, metric as metric_mod
from ..data.prefetcher import DevicePrefetcher, default_depth


def _maybe_prefetch(data):
    """Wrap a batch source in DevicePrefetcher (unless prefetch is
    disabled via MXTPU_DEVICE_PREFETCH=0, or it's already wrapped)."""
    if data is None or isinstance(data, DevicePrefetcher) \
            or default_depth() <= 0:
        return data
    return DevicePrefetcher(data)


class Estimator:
    def __init__(self, net, loss, metrics=None, trainer=None):
        self.net = net
        self.loss = loss
        self.train_metrics = [metric_mod.create(m)
                              for m in (metrics or ["acc"])]
        self.trainer = trainer

    def evaluate(self, val_data, metrics=None):
        metrics = [metric_mod.create(m) for m in metrics] \
            if metrics else self.train_metrics
        for m in metrics:
            m.reset()
        for batch in val_data:
            x, y = self._split(batch)
            out = self.net(x)
            for m in metrics:
                m.update([y], [out])
        return [m.get() for m in metrics]

    @staticmethod
    def _split(batch):
        if isinstance(batch, (list, tuple)):
            return batch[0], batch[1]
        return batch.data[0], batch.label[0]

    def fit(self, train_data, val_data=None, epochs=1,
            batch_end_callback=None, epoch_end_callback=None):
        # device prefetch: batch N+1's h2d copy overlaps batch N's step
        train_data = _maybe_prefetch(train_data)
        val_data = _maybe_prefetch(val_data)
        for epoch in range(epochs):
            tic = time.time()
            for m in self.train_metrics:
                m.reset()
            if hasattr(train_data, "reset"):
                train_data.reset()
            nbatch = 0
            for batch in train_data:
                x, y = self._split(batch)
                with autograd.record():
                    out = self.net(x)
                    loss = self.loss(out, y)
                loss.backward()
                self.trainer.step(x.shape[0])
                for m in self.train_metrics:
                    m.update([y], [out])
                nbatch += 1
                if batch_end_callback:
                    batch_end_callback(epoch, nbatch, self.train_metrics)
            if epoch_end_callback:
                epoch_end_callback(epoch, self.train_metrics,
                                   time.time() - tic)
            if val_data is not None:
                self.evaluate(val_data)
        return self
