"""LoRA — low-rank adaptation for fine-tuning (Hu et al. 2021).

Beyond reference scope (2018-era), but the natural fine-tuning story
for the transformer families this zoo ships: freeze the pretrained
weight W and learn a rank-r update ΔW = (alpha/r)·B·A, so the tuned
layer computes y = x·(W + ΔW)ᵀ + b.  TPU-fit: the adapter path is two
skinny MXU matmuls XLA fuses into the frozen base matmul's epilogue,
and the optimizer state shrinks to the adapter params (the dominant
memory cost of full fine-tuning).

Two surfaces:
- ``LoRADense``: drop-in ``nn.Dense`` wrapper owning frozen base
  weights + trainable A/B adapters, with ``merge()`` to fold the
  adapter into the base for deployment (exports as a plain matmul);
- ``apply_lora(net, rank, alpha, patterns)``: walk a built network and
  re-parameterize matching ``nn.Dense`` children in place.
"""

from __future__ import annotations

import re

from ..block import HybridBlock
from .. import nn


class LoRADense(nn.Dense):
    """Dense with a frozen base weight and trainable low-rank update.

    ``base`` (an initialized ``nn.Dense``) donates its weight/bias
    parameters, which are frozen (``grad_req='null'``); A is init'd
    normal, B zeros — the adapted layer starts EXACTLY equal to the
    base layer.  Subclasses ``nn.Dense`` so attribute rebinding and
    isinstance contracts on the wrapped net keep holding (Dense's own
    __init__ is bypassed: params come from ``base``)."""

    def __init__(self, base, rank=8, alpha=16.0, **kwargs):
        if not isinstance(base, nn.Dense):
            raise TypeError(f"LoRADense wraps nn.Dense, got {type(base)}")
        HybridBlock.__init__(self, **kwargs)  # skip Dense.__init__
        units, in_units = base.weight.shape
        if not in_units:
            raise ValueError(
                "LoRADense: base Dense has deferred (unknown) in_units — "
                "run a forward pass (or pass in_units=) before wrapping")
        self._units = units
        self._rank = int(rank)
        self._scale = float(alpha) / self._rank
        self._flatten = base._flatten
        self.act = base.act
        dtype = base.weight.dtype
        with self.name_scope():
            # shared handles: the base params THEMSELVES (not copies),
            # frozen, and registered under their original names so
            # collect_params()/save_parameters still carry them
            self.weight = base.weight
            self.weight.grad_req = "null"
            self.bias = base.bias
            if self.bias is not None:
                self.bias.grad_req = "null"
            self.params.update(base.params)
            # adapters match the base dtype: mixing would promote the
            # layer's output dtype (breaks bf16/amp paths)
            self.lora_a = self.params.get(
                "lora_a", shape=(self._rank, in_units), init="normal",
                dtype=dtype)
            self.lora_b = self.params.get(
                "lora_b", shape=(units, self._rank), init="zeros",
                dtype=dtype)

    def hybrid_forward(self, F, x, weight, lora_a, lora_b, bias=None):
        out = F.FullyConnected(x, weight, bias,
                               num_hidden=self._units,
                               no_bias=bias is None,
                               flatten=self._flatten)
        down = F.FullyConnected(x, lora_a, None, num_hidden=self._rank,
                                no_bias=True, flatten=self._flatten)
        up = F.FullyConnected(down, lora_b, None,
                              num_hidden=self._units, no_bias=True,
                              flatten=False)
        out = out + self._scale * up
        if self.act is not None:
            out = self.act(out)
        return out

    def merge(self):
        """Fold the adapter into the base weight; returns the (shared)
        base weight NDArray — after this, exporting/serving uses one
        plain matmul and the adapters can be dropped."""
        from ... import ndarray as nd

        w = self.weight.data()
        delta = nd.dot(self.lora_b.data(), self.lora_a.data())
        self.weight.set_data(w + self._scale * delta)
        # a merged adapter contributes zero until retrained
        self.lora_b.set_data(self.lora_b.data() * 0)
        # detach event: bump _cache_version so whole-step captures keyed
        # on this block (Trainer.train_step) rebuild, same as attach does
        # through register_child's clear
        self._clear_cached_op()
        return self.weight.data()


def freeze_for_lora(net):
    """Freeze every parameter whose name does not contain 'lora' —
    the fine-tuning recipe for models with BUILT-IN adapters (e.g.
    ``gpt.GPTModel(scan_layers=True, lora_rank=r)`` /
    ``ScanTransformerEncoder(lora_rank=r)``, whose trunk carries
    qkv_lora_a/b stacks).  Returns (n_trainable, n_total) param
    counts."""
    import numpy as _np

    n_train = n_total = 0
    for name, p in net.collect_params().items():
        n = int(_np.prod(p.shape)) if p.shape else 0
        n_total += n
        if "lora" in name:
            n_train += n
        else:
            p.grad_req = "null"
    if n_train == 0:
        raise ValueError("freeze_for_lora: net has no 'lora' params — "
                         "build it with lora_rank=... first")

    # grad_req flips don't touch the forward program, but caches keyed
    # on the block's structure version (the Trainer's captured
    # train_step folds the trainable set into its program) must see the
    # event — clear the whole tree like apply_lora does
    def _clear(block):
        if hasattr(block, "_clear_cached_op"):
            block._clear_cached_op()
        for c in block._children.values():
            _clear(c)

    _clear(net)
    return n_train, n_total


def apply_lora(net, rank=8, alpha=16.0, patterns=(".*",)):
    """Re-parameterize matching ``nn.Dense`` children of ``net`` with
    LoRA adapters in place; freezes every OTHER parameter too (the
    standard LoRA fine-tuning recipe).  Returns the list of new
    ``LoRADense`` blocks.  Call after the net is initialized and shapes
    are resolved (one forward pass)."""
    regs = [re.compile(p) for p in patterns]
    wrapped = []

    def visit(block):
        for name, child in list(block._children.items()):
            if isinstance(child, nn.Dense) and \
                    not isinstance(child, LoRADense) and \
                    any(r.search(child.name) for r in regs):
                ld = LoRADense(child, rank=rank, alpha=alpha,
                               prefix=child.prefix + "lora_")
                ld.lora_a.initialize()
                ld.lora_b.initialize()
                # register_child (not raw dict assignment): clears the
                # parent's cached jit/_param_order so a previously
                # hybridized-and-run net retraces WITH the adapters
                block.register_child(ld, name)
                # attribute references (e.g. self.fc1) must follow;
                # LoRADense IS-A Dense so __setattr__'s type gate holds
                for attr, val in vars(block).items():
                    if val is child:
                        setattr(block, attr, ld)
                wrapped.append(ld)
            else:
                visit(child)

    visit(net)
    # every ancestor holding a stale compiled forward must retrace too
    def clear(block):
        if hasattr(block, "_clear_cached_op"):
            block._clear_cached_op()
        for c in block._children.values():
            clear(c)

    clear(net)
    if not wrapped:
        raise ValueError(f"apply_lora: no nn.Dense matched {patterns}")
    lora_ids = {id(b.lora_a) for b in wrapped} \
        | {id(b.lora_b) for b in wrapped}
    for p in net.collect_params().values():
        if id(p) not in lora_ids:
            p.grad_req = "null"
    return wrapped
