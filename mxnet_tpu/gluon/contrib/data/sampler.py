"""Contrib samplers (reference: gluon/contrib/data/sampler.py)."""

from __future__ import annotations

from ...data.sampler import Sampler


class IntervalSampler(Sampler):
    """Samples i, i+interval, i+2*interval, ... for each start i —
    the strided-corpus sampler BPTT language-model training uses
    (reference: contrib.data.IntervalSampler)."""

    def __init__(self, length, interval, rollover=True):
        assert interval <= length, (
            f"IntervalSampler: interval {interval} must not exceed "
            f"length {length}")
        self._length = int(length)
        self._interval = int(interval)
        self._rollover = bool(rollover)

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            yield from range(i, self._length, self._interval)

    def __len__(self):
        if self._rollover:
            return self._length
        return len(range(0, self._length, self._interval))
