"""gluon.contrib.data (reference: python/mxnet/gluon/contrib/data/).

The reference ships text corpora (WikiText2/103) that download at use
time — impossible in this zero-egress image; `text.CharTokenDataset`
covers the same role over local files/strings.  The sampler utilities
are full parity.
"""

from . import sampler
from . import text
from .sampler import IntervalSampler
from .text import CharTokenDataset
