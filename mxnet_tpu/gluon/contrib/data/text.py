"""Language-model datasets over local text (reference:
gluon/contrib/data/text.py — WikiText2/WikiText103).

The reference datasets download their corpora at construction time;
this image has no egress, so the TPU rebuild provides the same
Dataset contract over a LOCAL file or string: vocabulary built from
the data, (seq_len,) int32 windows, the `seq_len`-strided layout the
reference's batchify produces.  Point it at any downloaded WikiText
copy and the reference training recipes run unchanged.
"""

from __future__ import annotations

import numpy as _np

from ...data.dataset import Dataset


class CharTokenDataset(Dataset):
    """Character-tokenized LM dataset: every item is (input_window,
    target_window) of ``seq_len`` int32 codes, windows strided by
    ``seq_len`` (non-overlapping, like the reference's bptt batchify).

    ``source`` is a path to a UTF-8 text file, or the text itself."""

    def __init__(self, source, seq_len=64, vocab=None):
        import os

        if isinstance(source, str) and os.path.exists(source):
            with open(source, encoding="utf-8") as f:
                text = f.read()
        elif isinstance(source, str) and (os.sep in source
                                          or source.endswith(".txt")):
            # looks like a path but doesn't exist: fail loudly (the
            # reference datasets do) instead of training on the path
            # string as a corpus
            raise FileNotFoundError(
                f"CharTokenDataset: no such file {source!r} (to pass "
                f"literal text containing '/', read the file yourself)")
        else:
            text = source
        if vocab is None:
            vocab = {c: i for i, c in enumerate(sorted(set(text)))}
        self.vocab = vocab
        self.inv_vocab = {i: c for c, i in vocab.items()}
        codes = _np.asarray([vocab[c] for c in text if c in vocab],
                            _np.int32)
        self._seq_len = int(seq_len)
        n = (len(codes) - 1) // self._seq_len
        if n <= 0:
            raise ValueError(
                f"text too short ({len(codes)} tokens) for "
                f"seq_len={seq_len}")
        usable = n * self._seq_len
        self._x = codes[:usable].reshape(n, self._seq_len)
        self._y = codes[1:usable + 1].reshape(n, self._seq_len)

    def __len__(self):
        return self._x.shape[0]

    def __getitem__(self, idx):
        return self._x[idx], self._y[idx]
