"""Contrib recurrent cells (reference: python/mxnet/gluon/contrib/rnn/ —
Conv*Cell, VariationalDropoutCell, LSTMPCell)."""

from __future__ import annotations

from ..rnn.rnn_cell import HybridRecurrentCell, ModifierCell


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask across time steps (reference:
    contrib.rnn.VariationalDropoutCell)."""

    def __init__(self, base_cell, drop_inputs=0., drop_states=0.,
                 drop_outputs=0.):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self._input_mask = None
        self._state_masks = None
        self._output_mask = None

    def _mask_like(self, F, arr, p):
        # one bernoulli mask, cached for the whole unroll
        return F.Dropout(F.ones_like(arr), p=p, _is_training=True)

    def hybrid_forward(self, F, inputs, states):
        if self.drop_inputs:
            if self._input_mask is None:
                self._input_mask = self._mask_like(F, inputs,
                                                   self.drop_inputs)
            inputs = inputs * self._input_mask
        if self.drop_states:
            if self._state_masks is None:
                self._state_masks = [
                    self._mask_like(F, s, self.drop_states)
                    for s in states]
            states = [s * m for s, m in zip(states, self._state_masks)]
        output, states = self.base_cell(inputs, states)
        if self.drop_outputs:
            if self._output_mask is None:
                self._output_mask = self._mask_like(F, output,
                                                    self.drop_outputs)
            output = output * self._output_mask
        return output, states


class Conv2DLSTMCell(HybridRecurrentCell):
    """Convolutional LSTM (Shi et al. 2015; reference:
    contrib.rnn.Conv2DLSTMCell).  Input (B, C, H, W)."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel=(3, 3),
                 h2h_kernel=(3, 3), i2h_pad=(1, 1),
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)  # (C, H, W)
        self._hc = hidden_channels
        self._i2h_kernel = i2h_kernel
        self._h2h_kernel = h2h_kernel
        self._i2h_pad = i2h_pad
        self._h2h_pad = (h2h_kernel[0] // 2, h2h_kernel[1] // 2)
        cin = self._input_shape[0]
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_channels, cin) + i2h_kernel,
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight",
            shape=(4 * hidden_channels, hidden_channels) + h2h_kernel,
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_channels,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_channels,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        c, h, w = self._input_shape
        return [{"shape": (batch_size, self._hc, h, w),
                 "__layout__": "NCHW"},
                {"shape": (batch_size, self._hc, h, w),
                 "__layout__": "NCHW"}]

    def _alias(self):
        return "conv_lstm"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hc, x.shape[1]) \
            + tuple(self._i2h_kernel)

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            num_filter=4 * self._hc)
        h2h = F.Convolution(states[0], h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            num_filter=4 * self._hc)
        gates = i2h + h2h
        i, f, g, o = F.SliceChannel(gates, num_outputs=4, axis=1)
        i = F.Activation(i, act_type="sigmoid")
        f = F.Activation(f, act_type="sigmoid")
        g = F.Activation(g, act_type="tanh")
        o = F.Activation(o, act_type="sigmoid")
        next_c = f * states[1] + i * g
        next_h = o * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]
