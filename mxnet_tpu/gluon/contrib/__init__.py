"""Gluon contrib (reference: python/mxnet/gluon/contrib/)."""

from . import cnn
from . import data
from . import lora
from . import nn
from . import rnn
from . import moe
from .lora import LoRADense, apply_lora, freeze_for_lora
from .estimator import Estimator
from .moe import MoEFFN
