"""Gluon contrib (reference: python/mxnet/gluon/contrib/)."""

from . import nn
from . import rnn
from . import moe
from .estimator import Estimator
from .moe import MoEFFN
