"""Contrib layers (reference: python/mxnet/gluon/contrib/nn/basic_layers.py
— Concurrent, HybridConcurrent, Identity, SparseEmbedding, SyncBatchNorm,
PixelShuffle)."""

from __future__ import annotations

from .. import nn
from ..block import Block, HybridBlock
from ..model_zoo.vision.squeezenet import HybridConcurrent  # canonical impl


class Concurrent(Block):
    """Parallel branches concatenated (reference: contrib.nn.Concurrent)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        from ... import ndarray as nd

        out = [block(x) for block in self._children.values()]
        return nd.concat(*out, dim=self.axis)


class Identity(HybridBlock):
    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def hybrid_forward(self, F, x):
        return x


class SparseEmbedding(nn.Embedding):
    """Reference: contrib.nn.SparseEmbedding (row_sparse grads).  Sparse
    gradients densify on TPU (XLA scatter-add is already the grad of
    gather), so this is the dense Embedding with the contrib name."""


class SyncBatchNorm(nn.BatchNorm):
    """Reference: contrib.nn.SyncBatchNorm (cross-GPU BN).

    Under ShardedTrainer the batch statistics are computed on the GLOBAL
    batch automatically — jnp.mean over a dp-sharded array IS the
    synchronized reduction (GSPMD inserts the psum) — so this is BatchNorm
    with the contrib name; num_devices is accepted and ignored.
    """

    def __init__(self, in_channels=0, num_devices=None, momentum=0.9,
                 epsilon=1e-5, center=True, scale=True,
                 use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones",
                 running_mean_initializer="zeros",
                 running_variance_initializer="ones", **kwargs):
        super().__init__(axis=1, momentum=momentum, epsilon=epsilon,
                         center=center, scale=scale,
                         use_global_stats=use_global_stats,
                         beta_initializer=beta_initializer,
                         gamma_initializer=gamma_initializer,
                         running_mean_initializer=running_mean_initializer,
                         running_variance_initializer=
                         running_variance_initializer,
                         in_channels=in_channels, **kwargs)


class PixelShuffle1D(HybridBlock):
    """(N, C·f, W) → (N, C, W·f) (reference: contrib.nn.PixelShuffle1D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = int(factor)

    def hybrid_forward(self, F, x):
        f = self._factor
        N, Cf, W = x.shape
        x = x.reshape((N, Cf // f, f, W))
        x = F.transpose(x, axes=(0, 1, 3, 2))
        return x.reshape((N, Cf // f, W * f))


class PixelShuffle2D(HybridBlock):
    """(N, C·f², H, W) → (N, C, H·f, W·f) (reference: PixelShuffle2D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        if isinstance(factor, int):
            factor = (factor, factor)
        self._factors = tuple(int(f) for f in factor)

    def hybrid_forward(self, F, x):
        f1, f2 = self._factors
        N, C, H, W = x.shape
        c = C // (f1 * f2)
        x = x.reshape((N, c, f1, f2, H, W))
        x = F.transpose(x, axes=(0, 1, 4, 2, 5, 3))
        return x.reshape((N, c, H * f1, W * f2))


class PixelShuffle3D(HybridBlock):
    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        if isinstance(factor, int):
            factor = (factor, factor, factor)
        self._factors = tuple(int(f) for f in factor)

    def hybrid_forward(self, F, x):
        f1, f2, f3 = self._factors
        N, C, D, H, W = x.shape
        c = C // (f1 * f2 * f3)
        x = x.reshape((N, c, f1, f2, f3, D, H, W))
        x = F.transpose(x, axes=(0, 1, 5, 2, 6, 3, 7, 4))
        return x.reshape((N, c, D * f1, H * f2, W * f3))
