"""Mixture-of-Experts gluon layers (Switch/GShard sparse FFN).

NEW, TPU-first (closes SURVEY.md §2.5's expert-parallel slot): the
reference has no MoE; this follows the public Switch-Transformer /
GShard design because its capacity-based dense dispatch is what XLA/TPU
compiles well.  Expert weights carry an ``ep`` leading axis — under a
mesh with an ``ep`` dimension (parallel.make_mesh(ep=...)) the
MOE_EP_RULES sharding places one expert group per ep slice and GSPMD
derives the dispatch/combine all-to-alls.

Usage::

    ffn = MoEFFN(units=512, hidden=2048, num_experts=8, k=2)
    rules = parallel.MOE_EP_RULES          # + TP rules if combining
    trainer = parallel.ShardedTrainer(net, loss, 'adamw', {...},
                                      mesh=parallel.make_mesh(dp=2, ep=4),
                                      rules=rules)
"""

from __future__ import annotations

from ..block import HybridBlock


class MoEFFN(HybridBlock):
    """Sparse MoE feed-forward block: router → top-k dispatch →
    per-expert FFN → weighted combine (op: ops/moe.py `moe_ffn`).

    Parameters
    ----------
    units : int
        Model width M (input/output features).
    hidden : int
        Per-expert FFN hidden width F.
    num_experts : int
        Number of experts E.
    k : int
        Experts per token (1 = Switch, 2 = GShard top-2).
    capacity_factor : float
        Per-expert capacity = ceil(tokens/E · capacity_factor).
    activation : str
        'relu' or 'gelu'.
    """

    def __init__(self, units, hidden, num_experts, k=1,
                 capacity_factor=1.25, activation="relu", **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._hidden = hidden
        self._num_experts = num_experts
        self._k = int(k)
        self._capacity_factor = float(capacity_factor)
        self._activation = activation
        self.aux_loss = None
        with self.name_scope():
            self.gate_weight = self.params.get(
                "gate_weight", shape=(num_experts, units))
            self.expert_w1 = self.params.get(
                "expert_ffn1_weight", shape=(num_experts, units, hidden))
            self.expert_b1 = self.params.get(
                "expert_ffn1_bias", shape=(num_experts, hidden), init="zeros")
            self.expert_w2 = self.params.get(
                "expert_ffn2_weight", shape=(num_experts, hidden, units))
            self.expert_b2 = self.params.get(
                "expert_ffn2_bias", shape=(num_experts, units), init="zeros")

    def hybrid_forward(self, F, x, gate_weight, expert_w1, expert_b1,
                       expert_w2, expert_b2):
        out = F.moe_ffn(x, gate_weight, expert_w1, expert_b1, expert_w2,
                        expert_b2, num_experts=self._num_experts,
                        k=self._k,
                        capacity_factor=self._capacity_factor,
                        activation=self._activation,
                        output_aux_loss=True)
        y, aux = out
        self._stash_aux(aux)
        return y

    def _stash_aux(self, aux):
        """Keep the load-balancing loss reachable for the training loop;
        under a jit trace this is a tracer — callers inside the same
        trace (e.g. a loss block) may read it, eager callers get the
        concrete value."""
        self.aux_loss = aux

    def __repr__(self):
        return (f"{self.__class__.__name__}(units={self._units}, "
                f"hidden={self._hidden}, experts={self._num_experts}, "
                f"k={self._k})")
