"""gluon.contrib.cnn (reference: python/mxnet/gluon/contrib/cnn/
conv_layers.py — DeformableConvolution).

The layer owns TWO kernels like the reference: a regular convolution
branch that predicts the per-tap sampling offsets, and the deformable
convolution (ops/vision_extra.py) that samples by them.
"""

from __future__ import annotations

from ..block import HybridBlock
from ..nn.activations import Activation
from ..nn.conv_layers import _to_tuple


class DeformableConvolution(HybridBlock):
    """2-D deformable convolution v1 (Dai et al. 2017).

    ``offset_*`` kwargs configure the offset-predicting convolution
    branch; the main branch consumes its output (reference signature
    kept)."""

    def __init__(self, channels, kernel_size=(1, 1), strides=(1, 1),
                 padding=(0, 0), dilation=(1, 1), groups=1,
                 num_deformable_group=1, layout="NCHW", use_bias=True,
                 in_channels=0, activation=None, weight_initializer=None,
                 bias_initializer="zeros",
                 offset_weight_initializer="zeros",
                 offset_bias_initializer="zeros", offset_use_bias=True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        assert layout == "NCHW", \
            "DeformableConvolution supports layout='NCHW'"
        kernel_size = _to_tuple(kernel_size, 2)
        strides = _to_tuple(strides, 2)
        padding = _to_tuple(padding, 2)
        dilation = _to_tuple(dilation, 2)
        with self.name_scope():
            self._channels = channels
            self._kwargs = {
                "kernel": kernel_size, "stride": strides, "pad": padding,
                "dilate": dilation, "num_filter": channels,
                "num_group": groups,
                "num_deformable_group": num_deformable_group,
                "no_bias": not use_bias}
            offset_channels = 2 * kernel_size[0] * kernel_size[1] \
                * num_deformable_group
            self._offset_kwargs = {
                "kernel": kernel_size, "stride": strides, "pad": padding,
                "dilate": dilation, "num_filter": offset_channels,
                "num_group": 1, "no_bias": not offset_use_bias,
                "layout": layout}
            self.weight = self.params.get(
                "weight",
                shape=(channels, in_channels // groups) + kernel_size,
                init=weight_initializer, allow_deferred_init=True)
            self.bias = self.params.get(
                "bias", shape=(channels,), init=bias_initializer,
                allow_deferred_init=True) if use_bias else None
            # zero-init offsets: the layer starts as a plain convolution
            # (the reference's deformable_conv_offset_initializer)
            self.offset_weight = self.params.get(
                "offset_weight",
                shape=(offset_channels, in_channels) + kernel_size,
                init=offset_weight_initializer, allow_deferred_init=True)
            self.offset_bias = self.params.get(
                "offset_bias", shape=(offset_channels,),
                init=offset_bias_initializer,
                allow_deferred_init=True) if offset_use_bias else None
            self.act = Activation(activation) if activation else None

    def infer_shape(self, x, *args):
        in_channels = x.shape[1]
        k = tuple(self._kwargs["kernel"])
        groups = self._kwargs["num_group"]
        self.weight.shape = (self._channels, in_channels // groups) + k
        self.offset_weight.shape = \
            (self.offset_weight.shape[0], in_channels) + k

    def hybrid_forward(self, F, x, weight, offset_weight, bias=None,
                       offset_bias=None):
        offset = F.Convolution(x, offset_weight, offset_bias,
                               **self._offset_kwargs)
        out = F.DeformableConvolution(
            x, offset, weight, bias, **self._kwargs)
        if self.act is not None:
            out = self.act(out)
        return out
