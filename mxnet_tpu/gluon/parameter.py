"""Gluon Parameter / ParameterDict.

Reference parity: python/mxnet/gluon/parameter.py — Parameter with deferred
shape init (shape entries of 0 solved at first forward), grad_req/lr_mult/
wd_mult, Constant, ParameterDict with prefix namespacing and shared params.

TPU-first notes: the reference keeps one copy of each parameter per GPU
(``list_data``); here a parameter is ONE logical array — multi-chip placement
is a *sharding* of that array over the mesh (jax.sharding), applied by the
Trainer/parallel layer, not by replicating handles.  ``list_data`` therefore
returns a single-element list.
"""

from __future__ import annotations

import re

import numpy as _np

from ..base import MXNetError, np_dtype
from ..context import Context, current_context, cpu
from ..ndarray.ndarray import NDArray, _from_jax
from .. import initializer


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its deferred shape was known."""


class Parameter:
    """A Block parameter (reference: gluon.Parameter)."""

    def __init__(self, name, grad_req="write", shape=None, dtype=_np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None,
                 allow_deferred_init=False, differentiable=True,
                 stype="default", grad_stype="default"):
        self._var = None
        self._data = None
        self._grad = None
        self._ctx_list = None
        self._deferred_init = ()
        self._differentiable = differentiable
        self._allow_deferred_init = allow_deferred_init
        self._grad_req = None
        if isinstance(shape, int):
            shape = (shape,)
        self._shape = tuple(shape) if shape is not None else None
        self.name = name
        self._dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.grad_req = grad_req
        self.init = init
        self._stype = stype
        self._grad_stype = grad_stype
        # sharding annotation (TPU-native extension): a
        # jax.sharding.PartitionSpec set by the parallel layer; applied when
        # the parameter is materialized inside a Mesh scope.
        self.partition_spec = None

    def __repr__(self):
        s = "Parameter {name} (shape={shape}, dtype={dtype})"
        return s.format(name=self.name, shape=self.shape, dtype=self.dtype)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        assert req in ("write", "add", "null"), \
            f"grad_req must be one of 'write', 'add', or 'null', but got {req}"
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                self._data._grad = None
                self._data._grad_req = "null"
        elif self._data is not None:
            self._init_grad()

    @property
    def dtype(self):
        return self._dtype

    @dtype.setter
    def dtype(self, dtype):
        self.cast(dtype)

    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if self._shape is None:
            self._shape = tuple(new_shape)
            return
        assert len(self._shape) == len(new_shape) and \
            all(j in (0, i) for i, j in zip(new_shape, self._shape)), \
            f"Expected shape {new_shape} is incompatible with given shape " \
            f"{self._shape}."
        self._shape = tuple(new_shape)

    @property
    def stype(self):
        return self._stype

    def _check_and_get(self, arr, ctx):
        if arr is not None:
            return arr
        if self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter '{self.name}' has not been initialized yet "
                "because initialization was deferred. Actual initialization "
                "happens during the first forward pass. Please pass one "
                "batch of data through the network before accessing "
                "Parameters.")
        raise RuntimeError(
            f"Parameter '{self.name}' has not been initialized. Note that "
            "you should initialize parameters and create Trainer with "
            "Block.collect_params() instead of Block.params because the "
            "later does not include Parameters of nested child Blocks")

    def _load_init(self, data, ctx=None, cast_dtype=False,
                   dtype_source="current"):
        """Load from a saved NDArray (reference: Parameter._load_init)."""
        if self.shape:
            unknown_dim_ok = any(s == 0 for s in self.shape)
            for self_dim, data_dim in zip(self.shape, data.shape):
                assert self_dim in (0, data_dim), \
                    f"Failed loading Parameter '{self.name}' from saved " \
                    f"params: shape incompatibility, expected {self.shape} " \
                    f"vs saved {data.shape}"
            self._shape = data.shape
        if self.dtype is not None and not cast_dtype:
            if _np.dtype(self.dtype).type != _np.dtype(data.dtype).type:
                raise AssertionError(
                    f"Failed loading Parameter '{self.name}' from saved "
                    f"params: dtype incompatibility, expected "
                    f"{self.dtype} vs saved {data.dtype}. Set cast_dtype=True "
                    "to cast the dtype of saved params.")
        elif cast_dtype:
            if dtype_source == "current":
                data = data.astype(self.dtype)
            elif dtype_source == "saved":
                self._dtype = data.dtype
        self._init_impl(data)
        self._deferred_init = ()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init_fn, default_init, ctx = self._deferred_init
        self._deferred_init = ()
        assert self.shape is not None and all(s > 0 for s in self.shape), \
            f"Cannot initialize Parameter '{self.name}' because it has " \
            f"invalid shape: {self.shape}."
        self._init_impl_from_init(init_fn, default_init, ctx)

    def _init_impl_from_init(self, init_fn, default_init, ctx):
        """Materialize + run initializers.  A specific init (the `init`
        argument or self.init) rides in InitDesc attrs and takes precedence
        over the global initializer's name-suffix dispatch (reference:
        Parameter._init_impl + attrs['__init__'])."""
        import jax.numpy as jnp

        data = _from_jax(jnp.zeros(self.shape, dtype=np_dtype(self.dtype)))
        specific = init_fn if init_fn is not None else self.init
        dispatcher = initializer.create(
            default_init if default_init is not None else "uniform")
        attrs = {"__init__": specific} if specific is not None else {}
        dispatcher(initializer.InitDesc(self.name, attrs), data)
        self._init_impl(data, ctx)

    def _init_impl(self, data, ctx=None):
        if not isinstance(data, NDArray):
            import jax.numpy as jnp

            data = _from_jax(jnp.asarray(data, dtype=np_dtype(self.dtype)))
        self._data = data
        self._ctx_list = [ctx or current_context()]
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        self._data.attach_grad(self._grad_req, stype=self._grad_stype)
        self._grad = self._data._grad

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """Materialize the parameter (reference: Parameter.initialize).

        Deferred when the shape still contains unknown (0) dims and
        allow_deferred_init is set.
        """
        if self._data is not None and not force_reinit:
            return
        if default_init is None:
            default_init = initializer.Uniform()
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self.shape is None or any(s <= 0 for s in self.shape):
            if self._allow_deferred_init:
                self._deferred_init = (init, default_init,
                                       ctx[0] if ctx else None)
                return
            raise ValueError(
                f"Cannot initialize Parameter '{self.name}' because it has "
                f"invalid shape: {self.shape}.")
        self._deferred_init = ()
        self._init_impl_from_init(init, default_init,
                                  ctx[0] if ctx else None)

    def reset_ctx(self, ctx):
        if self._data is not None:
            self._data = self._data.as_in_context(
                ctx[0] if isinstance(ctx, (list, tuple)) else ctx)
            if self._grad_req != "null":
                self._init_grad()

    def set_data(self, data):
        """Set the value on every context (reference: Parameter.set_data)."""
        self.shape = data.shape
        if self._data is None:
            assert self._deferred_init, \
                f"Parameter '{self.name}' has not been initialized"
            self._init_impl(data if isinstance(data, NDArray)
                            else _from_jax(data))
            self._deferred_init = ()
            return
        raw = data._data if isinstance(data, NDArray) else data
        self._data._set_data(raw.astype(self._data._data.dtype)
                             if hasattr(raw, "astype") else raw)

    def row_sparse_data(self, row_id):
        return self.data()

    def list_row_sparse_data(self, row_id):
        return [self.data()]

    def data(self, ctx=None):
        return self._check_and_get(self._data, ctx)

    def list_data(self):
        return [self._check_and_get(self._data, None)]

    def grad(self, ctx=None):
        if self._data is not None and self._grad is None:
            raise RuntimeError(
                f"Cannot get gradient array for Parameter '{self.name}' "
                "because grad_req='null'")
        self._check_and_get(self._data, ctx)
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return [self._deferred_init[2] or current_context()]
            raise RuntimeError(f"Parameter '{self.name}' has not been "
                               "initialized")
        return list(self._ctx_list)

    def zero_grad(self):
        if self._grad is None:
            return
        import jax.numpy as jnp

        from ..ndarray.sparse import RowSparseNDArray

        if isinstance(self._grad, RowSparseNDArray):
            # drop to the empty compact form — densifying a big
            # embedding's grad just to zero it would be O(table)
            g = self._grad
            g._set_sparse(jnp.zeros((0,), jnp.int32),
                          jnp.zeros((0,) + g.shape[1:],
                                    g._rs_values.dtype))
            return
        self._grad._set_data(jnp.zeros_like(self._grad._data))

    def cast(self, dtype):
        self._dtype = np_dtype(dtype)
        if self._data is None:
            return
        self._data._set_data(self._data._data.astype(np_dtype(dtype)))
        if self._grad_req != "null":
            self._init_grad()

    def var(self):
        """Symbol placeholder for this parameter (reference: Parameter.var)."""
        from .. import symbol

        if self._var is None:
            self._var = symbol.var(self.name, shape=self.shape,
                                   dtype=self.dtype, lr_mult=self.lr_mult,
                                   wd_mult=self.wd_mult, init=self.init)
        return self._var

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_var"] = None
        return state


class Constant(Parameter):
    """Non-trainable constant parameter (reference: gluon.Constant)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            import jax.numpy as jnp

            value = _from_jax(jnp.asarray(_np.asarray(value)))
        self.value = value

        class Init(initializer.Initializer):
            def _init_weight(self, _, arr):
                arr._set_data(value._data.astype(arr._data.dtype))

        init_name = f"Constant_{name}_{id(self)}"
        initializer._INIT_REGISTRY[init_name.lower()] = Init
        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=init_name.lower())


class ParameterDict:
    """Ordered dict of Parameters with prefix (reference:
    gluon.ParameterDict)."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = {}
        self._shared = shared

    def __repr__(self):
        s = "{name}(\n{content}\n)"
        name = self._prefix + " " if self._prefix else ""
        return s.format(name=name, content="\n".join(
            [repr(v).replace("\n", "\n  ") for v in self.values()]))

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __len__(self):
        return len(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._shared._params[name]
        return None

    def get(self, name, **kwargs):
        """Get-or-create ``self.prefix + name`` (reference semantics: found
        params must be attribute-compatible with kwargs)."""
        name = self.prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and len(v) == len(existing):
                        inferred_shape = []
                        matched = True
                        for dim1, dim2 in zip(v, existing):
                            if dim1 != dim2 and dim1 * dim2 != 0:
                                matched = False
                                break
                            inferred_shape.append(max(dim1, dim2))
                        if matched:
                            param._shape = tuple(inferred_shape)
                            continue
                    elif k == "dtype" and _np.dtype(v) == _np.dtype(existing):
                        continue
                    assert v is None or v == existing, \
                        f"Cannot retrieve Parameter '{name}' because " \
                        f"desired attribute does not match with stored for " \
                        f"attribute '{k}': desired '{v}' vs stored " \
                        f"'{getattr(param, k)}'"
                else:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self.prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(
                    f"No constant named '{name}'. Please specify value if "
                    "you want to create a new constant.")
            param = Constant(name, value)
            self._params[name] = param
        elif value is not None:
            assert isinstance(param, Constant), \
                f"Parameter '{name}' already exists but it is not a constant."
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params:
                assert self._params[k] is v, \
                    f"Cannot update self with other because they have " \
                    f"different Parameters with the same name '{k}'"
            else:
                self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        if verbose and init is not None:
            init.set_verbosity(verbose=verbose)
        for _, v in self.items():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def list_ctx(self):
        s = set()
        for v in self.values():
            s.update(v.list_ctx())
        return list(s)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import save as nd_save

        arg_dict = {}
        for param in self.values():
            weight = param.data()
            if not param.name.startswith(strip_prefix):
                raise ValueError(
                    f"Prefix '{strip_prefix}' is to be striped before "
                    f"saving, but Parameter's name '{param.name}' does not "
                    "start with it")
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd_save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix="", cast_dtype=False,
             dtype_source="current"):
        from ..ndarray import load as nd_load

        if restore_prefix:
            for name in self.keys():
                assert name.startswith(restore_prefix), \
                    f"restore_prefix is '{restore_prefix}' but Parameter " \
                    f"name '{name}' does not start with it"
        lprefix = len(restore_prefix)
        loaded = nd_load(filename)
        arg_dict = {(restore_prefix + k[4:] if k.startswith(("arg:", "aux:"))
                     else restore_prefix + k): v for k, v in loaded.items()}
        if not allow_missing:
            for name in self.keys():
                assert name in arg_dict, \
                    f"Parameter '{name[lprefix:]}' is missing in file " \
                    f"'{filename}'"
        for name in arg_dict:
            if name not in self._params:
                assert ignore_extra, \
                    f"Parameter '{name[lprefix:]}' loaded from file " \
                    f"'{filename}' is not present in ParameterDict"
                continue
            self[name]._load_init(arg_dict[name], ctx,
                                  cast_dtype=cast_dtype,
                                  dtype_source=dtype_source)
