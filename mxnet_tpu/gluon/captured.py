"""Whole-step graph capture for the imperative Gluon Trainer.

`ShardedTrainer` already compiles its entire step into one XLA program;
the imperative path — the one the tests, examples and the trainer bench
exercise — paid 4+ dispatches per step: the CachedOp forward, the
tape backward, the health reduction, and one GroupedUpdater program per
param group (plus per-microbatch grad-accumulate dispatches).  This
module is the CachedOp idea applied to the *whole step*: given a
hybridized block, a loss, and the Trainer's configuration, trace

    forward → loss → backward → (accumulate over microbatches)
    → health guard → global-norm clip → optimizer update

into ONE donated `jax.jit` program, cached per signature with the same
keying discipline `GroupedUpdater` established.  Per-step scalars (lr,
wd, rescale_grad, loss scale, t-folded coefficients) enter as traced
arrays (`optimizer.grouped.dyn_columns`), so LR schedules and
loss-scale changes never retrace.  ``grad_accum=k`` becomes a
`lax.scan` over microbatches inside the program, with BatchNorm-style
aux state threaded through the carry exactly as the eager path writes
it back between microbatches.

Pipeline parallelism (PR 17) lives INSIDE the same program: when the
parameters sit on a mesh with a ``pp`` axis (PPRules claims the scanned
trunk's leading layer-stack dim), the grad-accum scan is restructured
into a 1F1B-style shifted-carry schedule over ``grad_accum ×
pp_microbatches`` slices — each tick drains the previous microbatch's
gradients (handed to their stages with `with_sharding_constraint` on
the pp axis) while the current microbatch's stages compute, letting XLA
overlap cross-stage traffic with compute.  Still ONE donated jit, one
dispatch + one readback per step; ``MXTPU_PP=0`` or pp=1 degenerates to
the flat scan byte-for-byte.

Bitwise-parity discipline (PR 2/4): the eager multi-dispatch path stays
as the oracle behind ``MXTPU_CAPTURED_STEP=0``.  The captured trace
re-uses the exact same math homes — `block.param_override_scope` +
`random.key_scope` for the forward, `numerics.health_of` for the guard,
`optimizer.grouped.build_group_step` for the update — and reproduces
every eager *program boundary* with `_cut` (a custom-vjp
`lax.optimization_barrier`), because XLA's fusion/FMA-contraction
decisions are free to differ across a program boundary but not inside
one.  Cuts sit where the eager path materializes arrays: the CachedOp
forward output, the backward's gradient outputs, each grad-accumulate
sum, the loss-scale seed, and the health array.  Skip-step semantics
ride on the same `lax.cond` branches as the eager grouped programs, and
the host still performs EXACTLY one readback per step, after the update
dispatch (`numerics.StepGuard`).

Row-sparse embedding gradients (PR 18) run INSIDE the program too, for
`embedding.ShardedEmbedding` tables under SGD/Adam lazy updates: the
host computes unique ids + inverse index per step (`embedding.prep`),
pads the unique count to a power-of-two bucket folded into the capture
key, and the program pre-gathers just the touched rows, differentiates
through a gather-by-inverse lookup, and scatters the row update back
with `optimizer.grouped.sparse_row_kernel` — still one dispatch + one
readback.  ``MXTPU_SPARSE_CAPTURED=0`` pins sparse configs to the
eager row-sparse oracle.

What cannot be captured falls back to the eager oracle, per-step:
non-hybridized blocks, optimizers outside the fused-plan table,
multi-precision params, remat-enabled blocks, kvstore-backed reduction
(`kvstore.captured_step_compatible`), batch sizes not divisible by
``grad_accum``, sparse tables under a pipeline schedule or overflowing
a fixed MXTPU_UNIQUE_BUCKET, and steps with a pending ``nan_grad``
fault injection (the poison has no gradient buffer to land in on the
captured path).  A sparse fallback is never silent — the trainer emits
a ``sparse_fallback{reason}`` telemetry event.
"""

from __future__ import annotations

import os

_SENTINEL_UNSET = object()


def captured_step_enabled() -> bool:
    """MXTPU_CAPTURED_STEP gate (default on); 0/false/off routes
    `Trainer.train_step` to the eager multi-dispatch oracle."""
    return os.environ.get("MXTPU_CAPTURED_STEP", "1").lower() \
        not in ("0", "false", "off", "")


def pp_enabled() -> bool:
    """MXTPU_PP gate (default on); 0/false/off keeps the captured step
    on the flat grad-accum scan even when the mesh has a pp axis — the
    degenerate path is byte-identical to the pre-pipeline program."""
    return os.environ.get("MXTPU_PP", "1").lower() \
        not in ("0", "false", "off", "")


def resolve_pp_schedule(mesh, grad_accum, batch):
    """(pp_stages, pp_microbatches, total_slices) for this step.

    The 1F1B schedule is active only when the params sit on a mesh with
    a pp axis of size > 1 AND `pp_enabled()`; otherwise (1, 1, k) — the
    flat grad-accum scan.  ``pp_microbatches`` comes from the autotune
    knob (MXTPU_PP_MICROBATCHES; 0 = auto = the stage count), and the
    total slice count n = k*m must divide the batch: unlike the silent
    eager fallback for a batch indivisible by ``grad_accum`` alone, an
    indivisible microbatch split is a configuration the user asked for
    explicitly, so it raises UP FRONT naming both knobs.
    """
    k = int(grad_accum)
    stages = 1 if mesh is None else int(mesh.shape.get("pp", 1))
    if stages <= 1 or not pp_enabled():
        return 1, 1, k
    from ..autotune import space as _tune_space

    knob = _tune_space.KNOBS.get("pp_microbatches")
    try:
        m = int(knob.current()) if knob is not None else 0
    except ValueError:
        m = 0
    if m <= 0:
        m = stages
    n = k * m
    if batch % n != 0:
        raise ValueError(
            f"pipeline schedule: batch {batch} is not divisible by "
            f"grad_accum ({k}) * pp_microbatches ({m}) = {n} slices — "
            "pick grad_accum / MXTPU_PP_MICROBATCHES whose product "
            "divides the batch, or set MXTPU_PP=0")
    return stages, m, n


# -- accounting (regression-tested) --------------------------------------------
#
# dispatch: exactly ONE per captured step.  trace: increments only when
# jit actually re-traces pure_step (a python side effect in the traced
# body) — the retrace-regression tests pin this at one per signature.
# hits/misses: Trainer-level capture-cache stats, reported by bench.py.

_DISPATCH_COUNT = 0
_TRACE_COUNT = 0
_CACHE_HITS = 0
_CACHE_MISSES = 0


def dispatch_count() -> int:
    return _DISPATCH_COUNT


def trace_count() -> int:
    return _TRACE_COUNT


def cache_stats() -> dict:
    return {"hits": _CACHE_HITS, "misses": _CACHE_MISSES}


def reset_counters() -> None:
    global _DISPATCH_COUNT, _TRACE_COUNT, _CACHE_HITS, _CACHE_MISSES
    _DISPATCH_COUNT = 0
    _TRACE_COUNT = 0
    _CACHE_HITS = 0
    _CACHE_MISSES = 0


# -- the program-boundary cut --------------------------------------------------

_CUT = None


def _cut_fn():
    """Identity with an `optimization_barrier` on both the primal and the
    cotangent: XLA may not fuse or FMA-contract across it, in either
    direction.  Placed wherever the eager oracle crosses a compiled
    program boundary (a materialized array), so the captured program's
    arithmetic is partitioned exactly like the eager dispatch chain —
    the PR 2 lesson ("XLA FMA contraction differs across eager
    dispatches") applied in reverse."""
    global _CUT
    if _CUT is None:
        import jax

        @jax.custom_vjp
        def cut(x):
            return jax.lax.optimization_barrier(x)

        def cut_fwd(x):
            return jax.lax.optimization_barrier(x), None

        def cut_bwd(_res, ct):
            return (jax.lax.optimization_barrier(ct),)

        cut.defvjp(cut_fwd, cut_bwd)
        _CUT = cut
    return _CUT


# -- eligibility ---------------------------------------------------------------

def _raw(x):
    return getattr(x, "_data", x)


def _arg_specs_of(args):
    """Abstract (shape, dtype) skeleton of one dispatch's arguments —
    enough to re-lower the program for cost analysis after the real
    buffers were donated.  Returns None when any leaf lacks an aval."""
    import jax
    import numpy as _np

    try:
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(
                _np.shape(a), getattr(a, "dtype", _np.asarray(a).dtype)),
            args)
    except Exception:
        return None


def ineligible_reason(trainer, block, loss_fn, data, grad_accum):
    """Why this (trainer, block, loss) combination cannot be captured,
    or None when it can.  Cheap checks only — group planning happens in
    `get_step` (it shares `plan_items` with the eager path)."""
    from ..optimizer import grouped as _grouped
    from . import block as _blockmod

    if not _grouped.fused_step_enabled():
        return "fused step disabled (MXTPU_FUSED_STEP=0)"
    from .. import kvstore as _kvs

    if not _kvs.captured_step_compatible(trainer._kvstore):
        return "kvstore reduction outside the program"
    if trainer._update_on_kvstore:
        return "update_on_kvstore"
    if type(trainer._optimizer) not in _grouped._PLANS:
        return f"optimizer {type(trainer._optimizer).__name__} has no " \
               "fused plan"
    if not isinstance(block, _blockmod.HybridBlock):
        return "block is not a HybridBlock"
    if not block._active:
        return "block is not hybridized"
    if not callable(loss_fn):
        return "loss is not callable"
    if isinstance(loss_fn, _blockmod.Block) \
            and not isinstance(loss_fn, _blockmod.HybridBlock):
        return "loss block is not a HybridBlock"
    k = int(grad_accum)
    if k < 1:
        return "grad_accum < 1"
    if data.shape[0] % k != 0:
        return f"batch {data.shape[0]} not divisible by grad_accum {k}"
    sparse = [(i, p) for i, p in enumerate(trainer._params)
              if p._grad_req != "null"
              and getattr(p, "_grad_stype", None) == "row_sparse"]
    if sparse:
        from .. import embedding as _embedding

        return _embedding.sparse_capture_reason(trainer, block, sparse)
    return None


def _mesh_sharding_of(trainer):
    """(mesh, fingerprint) of the trainer's parameter placements, or
    (None, None) when params are single-device.  The fingerprint —
    mesh axis sizes + every param's PartitionSpec string — joins the
    capture cache key: re-sharding a model (shard_model, a mesh
    reshape after gang recovery) MUST miss the cache, because the
    donated program's layouts were inferred from the old placements."""
    from jax.sharding import NamedSharding

    from ..parallel.sharding import mesh_of_params

    params = list(trainer._params)
    mesh = mesh_of_params(params)
    if mesh is None:
        return None, None
    fp = []
    for i, p in enumerate(params):
        raw = getattr(getattr(p, "_data", None), "_data", None)
        sh = getattr(raw, "sharding", None)
        if isinstance(sh, NamedSharding):
            fp.append((i, str(sh.spec)))
    return mesh, (tuple(sorted(mesh.shape.items())), tuple(fp))


def _tree_version(block):
    """DFS tuple of ``_cache_version`` over a block tree: any
    `_clear_cached_op` anywhere in the tree (parameter set, child
    registration, hybridize, cast, LoRA attach/detach/merge) changes
    this tuple and therefore misses the capture cache — even when the
    mutating code only cleared the leaf it touched."""
    versions = [getattr(block, "_cache_version", 0)]
    for child in getattr(block, "_children", {}).values():
        versions.extend(_tree_version(child))
    return tuple(versions)


def _collect_blocks_params(block, loss_fn):
    """Ordered (name, param) pairs over block + loss params, deduped by
    identity — the forward override map must cover every parameter the
    trace can read."""
    from . import block as _blockmod

    pairs, seen = [], set()
    sources = [block.collect_params()]
    if isinstance(loss_fn, _blockmod.Block):
        sources.append(loss_fn.collect_params())
    for params in sources:
        for name, p in params.items():
            if id(p) not in seen:
                seen.add(id(p))
                pairs.append((name, p))
    return pairs


# -- capture cache -------------------------------------------------------------

_MAX_CACHE = 8


def capture_cache_size():
    """FIFO capacity of the per-trainer capture cache.  Overridable via
    MXTPU_CAPTURE_CACHE (min 1): the default of 8 is enough for training
    configurations, but a process that also serves holds one AOT program
    per (batch × seq) bucket and needs head-room."""
    from ..base import getenv_int

    return max(1, getenv_int("MXTPU_CAPTURE_CACHE", _MAX_CACHE))


def get_step(trainer, block, loss_fn, data, label, grad_accum):
    """Return the (possibly cached) `CapturedStep` for this call
    signature, or None when the step must run on the eager oracle.

    The cache key is GroupedUpdater's keying discipline extended to the
    whole step: (block cache-version, loss cache-version, grad_req
    layout, optimizer group plans [kernel + static hyper-params +
    dtype], guard/clip/amp flags, batch shapes, grad_accum, device
    fingerprint).  Anything that invalidates the block's CachedOp —
    parameter set, child registration, hybridize, cast, LoRA
    attach/detach — bumps ``_cache_version`` and therefore misses here
    too.  Per-step scalars (lr, t, wd, rescale, loss scale) are NOT in
    the key: they enter the program as traced arrays.
    """
    global _CACHE_HITS, _CACHE_MISSES
    from .. import kvstore as _kvs
    from .. import numerics
    from ..optimizer import grouped as _grouped

    trainer._sparse_fallback_reason = None
    reason = ineligible_reason(trainer, block, loss_fn, data, grad_accum)
    if reason is not None:
        return None
    block._ensure_initialized(data)

    upd = trainer._updaters[0]
    trained = [(i, p) for i, p in enumerate(trainer._params)
               if p._grad_req != "null"]
    if not trained:
        return None
    block_param_ids = {id(p) for _n, p
                       in _collect_blocks_params(block, loss_fn)}
    if any(id(p) not in block_param_ids for _i, p in trained):
        return None  # trainer optimizes params the forward never sees
    indices = [i for i, _p in trained]
    weights = [p.data() for _i, p in trained]
    sparse_params = [(i, p) for i, p in trained
                     if getattr(p, "_grad_stype", None) == "row_sparse"]
    sparse_idx = {i for i, _p in sparse_params}
    # weights stand in for the DENSE grads: the captured cotangents are
    # cast to the parameter dtype, so groupability is decided by the
    # weight.  Row-sparse params pass their actual RowSparseNDArray
    # grad buffer so plan_items picks the sparse_row_kernel variant.
    grad_standins = [p._grad if i in sparse_idx else p.data()
                     for i, p in trained]
    groups, fallback = _grouped.plan_items(upd, indices, grad_standins,
                                           weights)
    if fallback:
        return None

    guard_on = numerics.grad_guard_enabled()
    clip = trainer._clip_norm()
    has_scaler = getattr(trainer, "_amp_loss_scaler", None) is not None
    k = int(grad_accum)
    plan_sig = tuple(
        gkey + (tuple(i for i, *_r in items),)
        for gkey, items in groups.items())
    mesh, mesh_fp = _mesh_sharding_of(trainer)
    # program-affecting knobs (remat policy from block flags or the
    # MXTPU_REMAT/autotune env, optimizer group splitting): a changed
    # value must MISS here and re-capture — the traced program differs.
    # Non-program knobs (bucket MB, prefetch, ...) stay out of the key:
    # their consumers re-read env at dispatch time, so a recompile
    # would buy nothing.
    from .. import integrity as _integrity
    from .. import remat as _remat
    from ..autotune import space as _tune_space

    remat_policy = _remat.env_default(dict(block._flags).get("remat"))
    # pipeline schedule: raises (does NOT fall back) on an indivisible
    # grad_accum × pp_microbatches split; n_micro lands in the key both
    # directly and via mesh_fp + the pp_microbatches program knob
    pp_stages, _pp_m, n_micro = resolve_pp_schedule(
        mesh, k, int(data.shape[0]))
    # sparse-table host prep runs EVERY call, before the key: the
    # padded unique-count bucket is part of the capture signature, so
    # retraces are bounded by the number of distinct buckets a workload
    # produces, not by per-batch unique counts
    sparse_meta, sparse_key = [], ()
    trainer._sparse_prep = None
    if sparse_params:
        if pp_stages > 1:
            # gradients live in the 1F1B shifted carry; a rows-shaped
            # pending slot per stage is a different schedule — decline
            trainer._sparse_fallback_reason = \
                "pipeline schedule with row-sparse tables"
            return None
        from .. import embedding as _embedding
        from .. import telemetry as _telemetry

        preps, why, lookup_us = _embedding.prepare_step(
            block, data, sparse_params)
        if preps is None:
            trainer._sparse_fallback_reason = why
            return None
        pos = {i: j for j, (i, _p) in enumerate(trained)}
        sparse_meta = [(pos[i], id(p)) for i, p in sparse_params]
        sparse_key = tuple((pos[i], pr.bucket)
                           for (i, _p), pr in zip(sparse_params, preps))
        n_ids = sum(pr.n_ids for pr in preps)
        _telemetry.note(
            lookup_us=float(lookup_us),
            unique_fraction=sum(pr.n_real for pr in preps)
            / max(n_ids, 1))
        trainer._sparse_prep = preps
    key = (
        id(block), _tree_version(block),
        id(loss_fn), _tree_version(loss_fn),
        bool(getattr(loss_fn, "_active", False)),
        tuple((i, p._grad_req) for i, p in enumerate(trainer._params)),
        plan_sig, guard_on, clip, has_scaler, k,
        tuple(data.shape), str(_raw(data).dtype),
        None if label is None else (tuple(label.shape),
                                    str(_raw(label).dtype)),
        _kvs.device_fingerprint(), mesh_fp,
        pp_stages, n_micro, sparse_key,
        remat_policy, _tune_space.program_knob_values(),
        # integrity attestation adds a program output (the state
        # fingerprint) — a toggled flag must re-capture, and the
        # disabled program is bitwise-identical to the pre-integrity one
        _integrity.fingerprint_enabled(),
    )
    cache = getattr(trainer, "_captured_cache", None)
    if cache is None:
        cache = trainer._captured_cache = {}
    step = cache.get(key)
    if step is not None:
        _CACHE_HITS += 1
        step._groups = groups  # fresh state/param references, same plan
        return step
    _CACHE_MISSES += 1
    step = CapturedStep(trainer, block, loss_fn, trained, groups,
                        guard_on=guard_on, clip=clip,
                        has_scaler=has_scaler, grad_accum=k,
                        has_label=label is not None, mesh=mesh,
                        remat=remat_policy, pp_stages=pp_stages,
                        n_micro=n_micro, sparse_meta=sparse_meta)
    cap = capture_cache_size()
    while len(cache) >= cap:
        evicted_key = next(iter(cache))
        cache.pop(evicted_key)
        # an eviction means the NEXT hit on that signature recompiles —
        # on a serving/training hybrid that is a latency cliff, so it is
        # always worth a telemetry line
        from .. import telemetry as _telemetry

        _telemetry.event("capture_cache_evict", cache_size=cap,
                         kept=len(cache))
    cache[key] = step
    return step


class CapturedStep:
    """One compiled train-step program + the host bookkeeping around it.

    The donated jit consumes (trained params, other/aux params,
    optimizer states, per-step dyn scalars, batch, keys, loss scale)
    and returns (new params, new others, new states, per-microbatch
    losses, health).  Host side per step: update-count bump + dyn
    column build (shared with GroupedUpdater), ONE dispatch, write-back
    of the donated outputs, then the guarded finalize with its single
    readback (`Trainer._finalize_guarded_step`).
    """

    def __init__(self, trainer, block, loss_fn, trained, groups,
                 guard_on, clip, has_scaler, grad_accum, has_label,
                 mesh=None, remat=None, pp_stages=1, n_micro=None,
                 sparse_meta=None):
        # [(position in `trained`, table param id)] for row-sparse
        # embedding tables whose lookup + update run in-program — the
        # program then takes trailing (sp_uniq, sp_inv) index tuples
        self._sparse = list(sparse_meta or [])
        # resolved remat policy (remat.py registry): checkpoint-style
        # policies wrap the per-microbatch forward+loss closure below;
        # 'save_every_k:N' instead applies inside the scanned trunk
        # (ops/attention.py reads the env at trace time)
        self._remat = remat
        # mesh the parameters are committed over (None = single-device):
        # batch inputs are placed over its dp axis, and the program's
        # param/state outputs are pinned to the input shardings so the
        # donated buffers round-trip without a layout change (a drifting
        # output sharding would retrace NEXT step's jit)
        self._mesh = mesh
        self._block = block
        self._loss_fn = loss_fn
        self._trained = trained          # [(trainer_index, Parameter)]
        self._groups = groups            # plan_items layout
        self._guard_on = bool(guard_on)
        self._clip = clip
        self._want_guard = bool(guard_on) or clip is not None
        self._has_scaler = bool(has_scaler)
        self._grad_accum = int(grad_accum)
        # 1F1B pipeline schedule (resolve_pp_schedule): total microbatch
        # slices the in-program scan runs over — grad_accum *
        # pp_microbatches when the mesh has a pp axis, else grad_accum
        self._pp_stages = int(pp_stages)
        self._n_micro = int(n_micro) if n_micro else int(grad_accum)
        self._has_label = bool(has_label)
        from . import block as _blockmod

        self._loss_keyed = isinstance(loss_fn, _blockmod.HybridBlock) \
            and bool(loss_fn._active)
        pairs = _collect_blocks_params(block, loss_fn)
        trained_ids = {id(p) for _i, p in trained}
        self._others = [(name, p) for name, p in pairs
                        if id(p) not in trained_ids]
        self._pos = {i: j for j, (i, _p) in enumerate(trained)}
        # MFU accounting (mxnet_tpu/telemetry.py): arg avals captured on
        # the first dispatch, cost analysis lowered lazily ONCE per
        # capture signature — never on the per-step path
        self._arg_specs = None
        self._flops = _SENTINEL_UNSET
        self._compiled = _SENTINEL_UNSET
        self._collective_bytes = _SENTINEL_UNSET
        self._peak_bytes = _SENTINEL_UNSET
        from .. import integrity as _integrity

        # integrity plane (integrity.py): when enabled, the program
        # grows a trailing STATIC ``attest`` flag and a sixth output —
        # the parameter+optimizer-state fingerprint, computed in-program
        # (zero extra dispatches) only by the attest-step specialization;
        # the non-attest specialization is the plain step plus a
        # constant-zeros output
        self._want_fp = _integrity.fingerprint_enabled()
        self._fn = self._build()

    # -- trace ------------------------------------------------------------------

    def _build(self):
        import jax
        import jax.numpy as jnp

        from .. import autograd as _ag
        from .. import numerics
        from .. import random as _random
        from ..optimizer import grouped as _grouped
        from . import block as _blockmod

        cut = _cut_fn()
        blk, loss_fn = self._block, self._loss_fn
        k = self._n_micro
        pp_sched = self._pp_stages > 1
        want_guard, guard_on, clip = \
            self._want_guard, self._guard_on, self._clip
        has_scaler, has_label = self._has_scaler, self._has_label
        loss_keyed = self._loss_keyed
        mesh = self._mesh
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(mesh, PartitionSpec())

            def _sh(p):
                s = p.data()._data.sharding
                return s if isinstance(s, NamedSharding) else repl

            train_shs = [_sh(p) for _i, p in self._trained]
            other_shs = [_sh(p) for _n, p in self._others]
        else:
            train_shs = other_shs = None
        train_ids = [id(p) for _i, p in self._trained]
        train_dtypes = [p.data()._data.dtype for _i, p in self._trained]
        # row-sparse tables: position in train_vals → slot in the
        # trailing (sp_uniq, sp_inv) argument tuples
        sparse_pos = [p for p, _pid in self._sparse]
        sparse_param_ids = [pid for _p, pid in self._sparse]
        sp_of = {p: j for j, p in enumerate(sparse_pos)}
        from contextlib import nullcontext

        if sparse_pos:
            from ..embedding import prep as _embprep
        other_ids = [id(p) for _n, p in self._others]
        other_names = [n for n, _p in self._others]
        group_meta = []                 # (pure group fn, grad positions)
        for gkey, items in self._groups.items():
            kernel, static_items = gkey[0], gkey[1]
            if want_guard:
                gfn = _grouped.build_group_step(
                    kernel, static_items, guarded=guard_on, clip=clip)
            else:
                gfn = _grouped.build_group_step(kernel, static_items)
            group_meta.append((gfn, [self._pos[i] for i, *_r in items]))

        from .. import remat as _remat

        remat_policy = self._remat

        def micro(train_vals, others, x_mb, y_mb, kb, kl, scale,
                  invs=()):
            base_pm = dict(zip(other_ids, others))

            def fwd(tv):
                pm = dict(base_pm)
                pm.update(zip(train_ids, tv))
                aux = {}
                # the capture scope hands ShardedEmbedding its
                # microbatch inverse-index tracer; it must wrap the
                # forward INSIDE fwd so a remat replay re-enters it
                scope = _embprep.capture_scope(
                    dict(zip(sparse_param_ids, invs))) if sparse_pos \
                    else nullcontext()
                with scope, _blockmod.param_override_scope(pm, aux), \
                        _ag.train_mode():
                    with _random.key_scope(kb):
                        out = blk.forward(x_mb)
                    # the eager CachedOp materializes `out` between the
                    # forward and loss programs (and the loss→block
                    # cotangent on the way back)
                    out = cut(out)
                    if loss_keyed:
                        with _random.key_scope(kl):
                            loss = loss_fn(out, y_mb) \
                                if y_mb is not None else loss_fn(out)
                    else:
                        loss = loss_fn(out, y_mb) \
                            if y_mb is not None else loss_fn(out)
                return loss, aux

            if remat_policy:
                # checkpoint-style remat around forward+loss: the
                # backward recomputes the wrapped region instead of
                # saving residuals.  Bitwise-neutral (jax.checkpoint
                # replays identical HLO), proven by
                # tests/test_autotune.py parity.  save_every_k is a
                # no-op here — it lives inside the scanned trunk.
                fwd = _remat.wrap(fwd, remat_policy)
            (loss, aux), vjp_fn = jax.vjp(fwd, list(train_vals))
            if has_scaler:
                # eager: `loss * loss_scale` is its own program, and
                # backward seeds ones over THAT — i.e. a full(scale)
                seed = cut(jnp.ones_like(loss)
                           * scale.astype(loss.dtype))
            else:
                seed = jnp.ones_like(loss)
            aux_zero = jax.tree_util.tree_map(jnp.zeros_like, aux)
            (tv_ct,) = vjp_fn((seed, aux_zero))
            gs = [cut(g if g.dtype == dt else g.astype(dt))
                  for g, dt in zip(tv_ct, train_dtypes)]
            new_others = [aux.get(n, ov)
                          for n, ov in zip(other_names, others)]
            return loss, gs, new_others

        def pure_step(train_vals, other_vals, state_vals, dyn_list,
                      xs, ys, keys_b, keys_l, scale, sp_uniq, sp_inv):
            global _TRACE_COUNT
            _TRACE_COUNT += 1  # python side effect: fires at trace only
            # sparse tables enter the forward as their PRE-GATHERED
            # unique rows (the out-of-range sentinel id clamps to the
            # last row under mode='clip' — deterministic filler no
            # inverse-index entry ever targets);
            # the vjp below then differentiates w.r.t. the ROWS, so
            # cotangents and the grad-accum carry are (bucket, dim)
            # shaped, never the full table
            lookup_vals = list(train_vals)
            for j, p in enumerate(sparse_pos):
                lookup_vals[p] = cut(jnp.take(
                    train_vals[p], sp_uniq[j], axis=0, mode="clip"))
            if k == 1:
                losses, grads, new_others = micro(
                    lookup_vals, other_vals, xs, ys, keys_b, keys_l,
                    scale, list(sp_inv))
            elif not pp_sched:
                def body(carry, sl):
                    acc, others = carry
                    loss, gs, others = micro(
                        lookup_vals, others, sl["x"], sl.get("y"),
                        sl["kb"], sl.get("kl"), scale,
                        [sl[f"si{j}"] for j in range(len(sparse_pos))])
                    # one eager `grad += ct` dispatch per microbatch
                    acc = [cut(a + g) for a, g in zip(acc, gs)]
                    return (acc, others), loss

                sl = {"x": xs, "kb": keys_b}
                if has_label:
                    sl["y"] = ys
                if loss_keyed:
                    sl["kl"] = keys_l
                for j in range(len(sparse_pos)):
                    sl[f"si{j}"] = sp_inv[j]
                acc0 = [jnp.zeros_like(v) for v in lookup_vals]
                (grads, new_others), losses = jax.lax.scan(
                    body, (acc0, list(other_vals)), sl)
            else:
                # 1F1B-style shifted-carry schedule: the carry holds the
                # PREVIOUS microbatch's gradients, and each tick drains
                # them into the accumulator while the CURRENT
                # microbatch's stages compute — the accumulate has no
                # data dependence on this tick's micro(), so XLA is free
                # to overlap its cross-stage (pp-axis) traffic with
                # microbatch i+1's stage-s compute, exactly the
                # comm/compute-overlap the schedule exists for.  The
                # sharding constraint hands each gradient slice to its
                # stage's devices (train_shs carries the pp placement of
                # the *_stack_* params).  Bitwise: tick 0 adds an exact
                # +0 array, after which the add chain sees operand-for-
                # operand the same barriered sums as the flat scan — so
                # captured(k, m) equals the eager oracle at
                # grad_accum=k*m (pinned by tests/test_pipeline_*).
                def body(carry, sl):
                    acc, pending, others = carry
                    acc = [cut(a + p) for a, p in zip(acc, pending)]
                    loss, gs, others = micro(
                        train_vals, others, sl["x"], sl.get("y"),
                        sl["kb"], sl.get("kl"), scale)
                    gs = [jax.lax.with_sharding_constraint(g, s)
                          for g, s in zip(gs, train_shs)]
                    return (acc, gs, others), loss

                sl = {"x": xs, "kb": keys_b}
                if has_label:
                    sl["y"] = ys
                if loss_keyed:
                    sl["kl"] = keys_l
                acc0 = [jnp.zeros_like(v) for v in train_vals]
                pend0 = [jnp.zeros_like(v) for v in train_vals]
                ((acc, pending, new_others), losses) = jax.lax.scan(
                    body, (acc0, pend0, list(other_vals)), sl)
                # cooldown drain: the last microbatch's grads are still
                # in flight when the scan ends
                grads = [cut(a + p) for a, p in zip(acc, pending)]
            if want_guard:
                hg = grads
                if sparse_pos:
                    # the eager guard reads the DENSE gradient view
                    # (RowSparseNDArray._data = zeros.at[ids].add(vals),
                    # its own dispatch): same formula here, with the
                    # out-of-bounds sentinel rows dropped by the scatter
                    hg = list(grads)
                    for j, p in enumerate(sparse_pos):
                        hg[p] = cut(jnp.zeros(
                            train_vals[p].shape,
                            grads[p].dtype).at[sp_uniq[j]].add(grads[p]))
                health = cut(numerics.health_of(hg))
            else:
                health = None
            new_train = list(train_vals)
            new_states = []
            for (gfn, pos), states, dyn in zip(group_meta, state_vals,
                                               dyn_list):
                ws = [train_vals[p] for p in pos]
                # a row-sparse grad reaches its kernel as (ids, values)
                gsl = [(sp_uniq[sp_of[p]], grads[p]) if p in sp_of
                       else grads[p] for p in pos]
                if want_guard:
                    nw, ns = gfn(ws, gsl, states, dyn, health)
                else:
                    nw, ns = gfn(ws, gsl, states, dyn)
                for p, w in zip(pos, nw):
                    new_train[p] = w
                if train_shs is not None:
                    # states shard with their weight (grouped kernels
                    # only ever see weight-shaped state)
                    ns = [[jax.lax.with_sharding_constraint(
                               a, train_shs[p]) for a in item_states]
                          for p, item_states in zip(pos, ns)]
                new_states.append(ns)
            if train_shs is not None:
                # pin param/aux outputs to their INPUT shardings: the
                # donated buffers must round-trip layout-stable or the
                # next dispatch sees new input shardings and retraces
                # (sits at the program tail, outside every cut/cond —
                # no fusion decision changes upstream of it)
                new_train = [jax.lax.with_sharding_constraint(v, s)
                             for v, s in zip(new_train, train_shs)]
                new_others = [jax.lax.with_sharding_constraint(v, s)
                              for v, s in zip(new_others, other_shs)]
            return new_train, new_others, new_states, losses, health

        if not self._want_fp:
            return jax.jit(pure_step, donate_argnums=(0, 1, 2))

        from .. import integrity as _integrity

        def pure_step_fp(train_vals, other_vals, state_vals, dyn_list,
                         xs, ys, keys_b, keys_l, scale, sp_uniq,
                         sp_inv, attest):
            # ``attest`` is STATIC: jit specializes into exactly two
            # executables (one trace + compile each, cached by jit).
            # The non-attest executable is the plain step plus a
            # constant-zeros output — XLA dead-code-eliminates the
            # whole fingerprint, so steady-state overhead is ~0.  (A
            # traced predicate under lax.cond was measurably worse:
            # every param+state array becomes a conditional operand,
            # which blocks fusion/aliasing on EVERY step.)
            new_train, new_others, new_states, losses, health = \
                pure_step(train_vals, other_vals, state_vals, dyn_list,
                          xs, ys, keys_b, keys_l, scale, sp_uniq,
                          sp_inv)
            if attest:
                flat_states = [a for group in new_states
                               for item in group for a in item]
                fp = _integrity.fingerprint_arrays(
                    list(new_train) + flat_states)
            else:
                fp = jnp.zeros((2,), jnp.uint32)
            return (new_train, new_others, new_states, losses, health,
                    fp)

        return jax.jit(pure_step_fp, donate_argnums=(0, 1, 2),
                       static_argnums=(11,))

    # -- per-step host driver ---------------------------------------------------

    def __call__(self, trainer, data, label, batch_size):
        global _DISPATCH_COUNT
        import numpy as _np

        import jax.numpy as jnp

        from .. import numerics, profiler
        from .. import random as _random
        from ..ndarray import _from_jax
        from ..optimizer import grouped as _grouped

        o = trainer._optimizer
        with profiler.annotate("captured_host_prep"):
            trainer._set_rescale(batch_size)
            indices = [i for i, _p in self._trained]
            snapshot = trainer._snapshot_update_counts(indices) \
                if self._guard_on else None
            for i in indices:
                o._update_count(i)
            state_vals, dyn_list = [], []
            for gkey, items in self._groups.items():
                state_vals.append([[s._data for s in st]
                                   for _i, _w, _g, st, _d in items])
                dyn_list.append(_grouped.dyn_columns(
                    o, items, _np.dtype(gkey[2])))
            # the in-program scan runs over n_micro slices (grad_accum ×
            # pp_microbatches under the pipeline schedule): one RNG key
            # per slice, batch reshaped to (n, b//n, ...) — matching the
            # key-draw count of the eager oracle at grad_accum=n_micro
            k = self._n_micro
            kbs, kls = [], []
            for _ in range(k):
                kbs.append(_random.next_key())
                if self._loss_keyed:
                    kls.append(_random.next_key())
        with profiler.annotate("captured_data"):
            if k == 1:
                keys_b = kbs[0]
                keys_l = kls[0] if kls else kbs[0]
                xs = _raw(data)
                ys = None if label is None else _raw(label)
            else:
                keys_b = jnp.stack(kbs)
                keys_l = jnp.stack(kls) if kls else keys_b
                xr = _raw(data)
                xs = xr.reshape((k, xr.shape[0] // k) + xr.shape[1:])
                ys = None
                if label is not None:
                    yr = _raw(label)
                    ys = yr.reshape((k, yr.shape[0] // k) + yr.shape[1:])
            if self._mesh is not None:
                # split the (micro)batch dim over dp: committed batch
                # placement, so GSPMD infers the data-parallel layout
                # instead of replicating the batch (leading=1 under
                # grad-accum — dim 0 is the scan axis)
                import jax

                from ..parallel.sharding import batch_sharding

                lead = 0 if k == 1 else 1
                xs = jax.device_put(xs, batch_sharding(
                    self._mesh, xs.shape[lead], leading=lead))
                if ys is not None:
                    ys = jax.device_put(ys, batch_sharding(
                        self._mesh, ys.shape[lead], leading=lead))
            # host-prepared sparse lookup indices (get_step ran
            # embedding.prepare_step before the cache lookup — possibly
            # just consuming the DevicePrefetcher's stash); the inverse
            # index reshapes to (n_micro, ids/micro) so each scan slice
            # sees exactly its microbatch's flat ids, batch-major like
            # the xs reshape above
            sp_uniq = sp_inv = ()
            if self._sparse:
                preps = trainer._sparse_prep
                trainer._sparse_prep = None
                sp_uniq = tuple(jnp.asarray(pr.uniq) for pr in preps)
                if k == 1:
                    sp_inv = tuple(jnp.asarray(pr.inv) for pr in preps)
                else:
                    sp_inv = tuple(jnp.asarray(pr.inv.reshape(
                        (k, pr.inv.size // k))) for pr in preps)
                if self._mesh is not None:
                    import jax
                    from jax.sharding import (NamedSharding,
                                              PartitionSpec)

                    repl = NamedSharding(self._mesh, PartitionSpec())
                    sp_uniq = tuple(jax.device_put(u, repl)
                                    for u in sp_uniq)
                    sp_inv = tuple(jax.device_put(v, repl)
                                   for v in sp_inv)
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        scale = _np.float32(scaler.loss_scale if scaler else 1.0)
        train_raws = [p.data()._data for _i, p in self._trained]
        other_raws = [p.data()._data for _n, p in self._others]
        if self._arg_specs is None:
            from .. import telemetry

            if telemetry.enabled():
                self._arg_specs = _arg_specs_of(
                    (train_raws, other_raws, state_vals, dyn_list,
                     xs, ys, keys_b, keys_l, scale, sp_uniq, sp_inv))
        fp = None
        with profiler.annotate("captured_step"):
            if self._want_fp:
                attest = bool(trainer._integrity_due())
                (new_train, new_others, new_states, losses, health,
                 fp) = self._fn(
                    train_raws, other_raws, state_vals, dyn_list,
                    xs, ys, keys_b, keys_l, scale, sp_uniq, sp_inv,
                    attest)
                if not attest:
                    fp = None
            else:
                new_train, new_others, new_states, losses, health = \
                    self._fn(
                        train_raws, other_raws, state_vals, dyn_list,
                        xs, ys, keys_b, keys_l, scale, sp_uniq, sp_inv)
        _DISPATCH_COUNT += 1
        for (_i, p), nw in zip(self._trained, new_train):
            p.data()._set_data(nw)
        for (_n, p), nv in zip(self._others, new_others):
            p.data()._set_data(nv)
        for (_gkey, items), ns_group in \
                zip(self._groups.items(), new_states):
            for (_i, _w, _g, st, _d), ns in zip(items, ns_group):
                for s_nd, s_new in zip(st, ns):
                    s_nd._set_data(s_new)
        from .. import resilience as _resilience

        if _resilience.fault_armed("bit_flip_param"):
            # memory-SDC injection: corrupt the LIVE post-step state
            # after the program committed — the in-program fingerprint
            # is clean, so the flip surfaces at the NEXT attestation
            # (within one interval) and a shadow replay disagrees with
            # the live state (kind="memory")
            from .. import integrity as _integrity

            _integrity.maybe_bit_flip_param(
                params=[p for _i, p in self._trained])
        trainer._step_count += 1
        if self._want_guard:
            guard = numerics.StepGuard(health, skip=self._guard_on,
                                       clip=self._clip, extra=fp)
            trainer._finalize_guarded_step(guard, snapshot)
        elif fp is not None:
            # no numerics guard: the attestation readback is the step's
            # one host sync instead
            from .. import integrity as _integrity

            trainer._integrity_attest(
                _integrity.combine(_np.asarray(fp)))
        return _from_jax(losses)

    # -- program accounting (mxnet_tpu/telemetry.py) ----------------------------

    def _compiled_for_stats(self):
        """The compiled step program re-lowered against the recorded
        arg avals — at most once per capture signature, with no device
        dispatch and no readback.  The retrace this lowering performs is
        excluded from `trace_count` (that counter pins RUNTIME
        retraces).  None when avals are unknown or lowering fails."""
        global _TRACE_COUNT
        if self._compiled is _SENTINEL_UNSET:
            self._compiled = None
            if self._arg_specs is not None:
                saved = _TRACE_COUNT
                try:
                    # the integrity program carries a trailing static
                    # attest flag: lower the non-attest specialization
                    # (the one every steady-state step runs)
                    args = tuple(self._arg_specs) + (False,) \
                        if self._want_fp else self._arg_specs
                    self._compiled = \
                        self._fn.lower(*args).compile()
                except Exception:
                    self._compiled = None
                finally:
                    _TRACE_COUNT = saved
        return self._compiled

    def cost_flops(self):
        """Total FLOPs of the compiled step program via XLA cost
        analysis, or None when unavailable."""
        if self._flops is _SENTINEL_UNSET:
            from .. import telemetry

            compiled = self._compiled_for_stats()
            self._flops = None if compiled is None \
                else telemetry.flops_of_compiled(compiled)
        return self._flops

    def memory_high_water(self):
        """Per-device memory high-water of the step program in bytes
        (arguments + outputs + XLA temp allocations, donation aliases
        counted once), or None when the compiler doesn't expose it."""
        if self._peak_bytes is _SENTINEL_UNSET:
            self._peak_bytes = None
            compiled = self._compiled_for_stats()
            if compiled is not None:
                try:
                    ma = compiled.memory_analysis()
                    total = (int(ma.temp_size_in_bytes)
                             + int(ma.argument_size_in_bytes)
                             + int(ma.output_size_in_bytes)
                             - int(getattr(ma, "alias_size_in_bytes",
                                           0)))
                    self._peak_bytes = max(total, 0)
                except Exception:
                    self._peak_bytes = None
        return self._peak_bytes

    def pipeline_stats(self):
        """Static 1F1B schedule accounting for this capture, or None on
        a non-pipelined program: stage count, microbatch slices, the
        warmup/cooldown slot counts, total schedule ticks, and the
        derived ``bubble_fraction`` = (S−1)/(n+S−1)
        (`parallel.pipeline.gpipe_bubble_fraction` — cross-checked by
        tests against `_schedule_1f1b`'s measured idle fraction).  When
        XLA cost analysis is available, ``flops_per_microbatch`` rides
        along so trace_report can sanity-check the bubble against the
        program's actual per-slice work."""
        if self._pp_stages <= 1:
            return None
        from ..parallel.pipeline import gpipe_bubble_fraction

        s, n = self._pp_stages, self._n_micro
        out = {
            "stages": s,
            "microbatches": n,
            "warmup": s - 1,
            "cooldown": s - 1,
            "ticks": n + s - 1,
            "bubble_fraction": float(gpipe_bubble_fraction(s, n)),
        }
        flops = self.cost_flops()
        if flops:
            out["flops_per_microbatch"] = float(flops) / max(n, 1)
        return out

    def collective_bytes_by_axis(self):
        """{axis: bytes-moved-per-device} over the step program's
        collectives (telemetry.collective_bytes_by_axis), or None on a
        single-device capture / when HLO is unavailable."""
        if self._collective_bytes is _SENTINEL_UNSET:
            self._collective_bytes = None
            if self._mesh is not None:
                from .. import telemetry

                compiled = self._compiled_for_stats()
                if compiled is not None:
                    self._collective_bytes = \
                        telemetry.collective_bytes_by_axis(
                            compiled, self._mesh)
        return self._collective_bytes
