"""Checkpointable input-pipeline state (exactly-once sample accounting).

NEW, TPU-first: the trainer side of the stack restores weights and
optimizer state bitwise across crashes and elastic reshapes
(checkpoint.AsyncCheckpointer manifests, PeerSnapshotStore RAM replicas),
but the reference input pipeline re-derives its position from scratch —
a resumed run re-reads or skips samples depending on where the crash
landed.  :class:`DataPipelineState` is the missing half: one small,
JSON-serializable record of WHERE the pipeline is (epoch, global sample
cursor, batch ordinal, quarantined batches) that `DataLoader`,
`DevicePrefetcher`, and the `io` iterators expose via
``state_dict()/load_state_dict()`` and that rides the existing
checkpoint path (stamped into MANIFEST.json and peer-snapshot frames by
`resilience.data_state_stamp`).

Exactness model
---------------
The epoch's global sample order is a **pure function of (seed, epoch)**
(:func:`epoch_order` — its own `numpy.random.Generator`, never the
global RNG), so any rank of any world size can reconstruct it.  The
cursor counts samples *delivered* this epoch, globally: rank ``r`` of
``w`` draws ``order[cursor:][r::w]``, which partitions the REMAINING
sample space of the in-flight epoch for ANY ``w`` — an elastic N→M
reshape just reloads the same state with the survivors' new
``rank/world`` and the partition re-shards itself with zero re-read and
zero skipped samples.  The cursor advances at batch *delivery* time
(never at prefetch/submission time), so prefetched-but-undelivered
batches are simply discarded on restore and re-fetched from the cursor.

Quarantine: batches a `numerics.DivergenceMonitor` rollback blamed are
identified by ``(epoch, batch ordinal)``; post-rollback replay consults
the set and skips them loudly (one ``batch_quarantined`` telemetry
event per skip, emitted by the consuming iterator) instead of
re-triggering the divergence.

This module is deliberately numpy+stdlib only — it loads standalone
(``bench.py``'s orchestrator keeps its driver jax-free) and in spawned
loader workers.
"""

from __future__ import annotations

import numpy as _np

#: bumped when the state_dict layout changes incompatibly
STATE_VERSION = 1


def epoch_order(seed, epoch, length, shuffle=True):
    """The global sample order of one epoch, as a numpy index array.

    A pure function of ``(seed, epoch)``: the permutation comes from a
    dedicated ``numpy.random.Generator`` seeded with exactly those two
    ints (never the global RNG), so every rank — and every *future*
    rank, after an elastic reshape — reconstructs the identical order.
    """
    if not shuffle:
        return _np.arange(int(length), dtype=_np.int64)
    rng = _np.random.default_rng([int(seed) & 0xffffffff, int(epoch)])
    return rng.permutation(int(length)).astype(_np.int64)


class DataPipelineState:
    """Position of a resumable input pipeline.

    Global fields (identical on every rank, adopted by
    ``load_state_dict``): ``seed``, ``shuffle``, ``epoch``, ``cursor``
    (samples consumed this epoch, across all ranks), ``batch_idx`` (batch
    rounds delivered or quarantine-skipped this epoch), ``samples_seen``
    (lifetime samples delivered, across all ranks), and the quarantine
    set.  Local fields (kept through ``load_state_dict`` — this is the
    N→M re-shard): ``rank`` and ``world``.
    """

    def __init__(self, length, seed=0, shuffle=True, rank=0, world=1):
        self.length = int(length)
        self.seed = int(seed)
        self.shuffle = bool(shuffle)
        self.rank = int(rank)
        self.world = int(world)
        if not 0 <= self.rank < self.world:
            raise ValueError(
                f"DataPipelineState: rank {self.rank} outside world "
                f"{self.world}")
        self.epoch = 0
        self.cursor = 0
        self.batch_idx = 0
        self.samples_seen = 0
        self.quarantined = set()   # {(epoch, batch_idx)}
        self.last_delivered = None  # (epoch, batch_idx) of newest batch

    # -- sharding --------------------------------------------------------------

    def order(self):
        return epoch_order(self.seed, self.epoch, self.length,
                           self.shuffle)

    def remaining(self):
        """Samples of the in-flight epoch not yet consumed (global)."""
        return max(0, self.length - self.cursor)

    def shard(self):
        """THIS rank's slice of the remaining epoch, in delivery order.

        ``order[cursor:][rank::world]`` — the union over ranks is
        exactly the un-consumed sample set, for any world size.
        """
        return self.order()[self.cursor:][self.rank::self.world]

    def shard_len(self):
        rem = self.remaining()
        if rem <= self.rank:
            return 0
        return (rem - self.rank + self.world - 1) // self.world

    # -- accounting (delivery order only) --------------------------------------

    def _global_advance(self, n_local):
        """Samples the whole gang consumed when this rank consumed
        ``n_local``: every rank's round draws from the same interleaved
        remainder, so one round is ``n_local * world`` capped at what
        was left (ragged final round)."""
        return min(int(n_local) * self.world, self.remaining())

    def advance(self, n_local):
        """One batch of ``n_local`` samples DELIVERED on this rank."""
        adv = self._global_advance(n_local)
        self.cursor += adv
        self.samples_seen += adv
        self.last_delivered = (self.epoch, self.batch_idx)
        self.batch_idx += 1
        return adv

    def skip(self, n_local):
        """One quarantined batch skipped: the cursor moves past its
        samples but nothing was delivered (``samples_seen`` untouched)."""
        adv = self._global_advance(n_local)
        self.cursor += adv
        self.batch_idx += 1
        return adv

    def next_epoch(self):
        self.epoch += 1
        self.cursor = 0
        self.batch_idx = 0

    # -- quarantine ------------------------------------------------------------

    @staticmethod
    def _batch_id(bid):
        if isinstance(bid, (tuple, list)) and len(bid) == 2:
            return (int(bid[0]), int(bid[1]))
        raise ValueError(
            f"batch id must be an (epoch, batch_idx) pair, got {bid!r}")

    def quarantine(self, batch_ids):
        """Add ``(epoch, batch_idx)`` ids to the quarantine set."""
        for bid in batch_ids:
            self.quarantined.add(self._batch_id(bid))

    def is_quarantined(self, epoch, batch_idx):
        return (int(epoch), int(batch_idx)) in self.quarantined

    # -- (de)serialization -----------------------------------------------------

    def state_dict(self):
        """JSON-serializable snapshot (rides MANIFEST.json verbatim)."""
        return {
            "version": STATE_VERSION,
            "length": self.length,
            "seed": self.seed,
            "shuffle": self.shuffle,
            "rank": self.rank,
            "world": self.world,
            "epoch": self.epoch,
            "cursor": self.cursor,
            "batch_idx": self.batch_idx,
            "samples_seen": self.samples_seen,
            "quarantined": sorted([list(q) for q in self.quarantined]),
        }

    def load_state_dict(self, sd):
        """Adopt a snapshot's GLOBAL position; keep the local
        rank/world (an N→M reshape is just a load under new ones).
        Raises ``ValueError`` on a version or dataset-length mismatch —
        silently mis-aligning the sample stream is the one failure mode
        this subsystem exists to prevent."""
        if not isinstance(sd, dict):
            raise ValueError(
                f"data pipeline state must be a dict, got "
                f"{type(sd).__name__}")
        version = sd.get("version")
        if version != STATE_VERSION:
            raise ValueError(
                f"data pipeline state version {version!r} "
                f"(this build reads {STATE_VERSION})")
        if int(sd["length"]) != self.length:
            raise ValueError(
                f"data pipeline state is for a dataset of "
                f"{sd['length']} samples; this loader has {self.length}")
        self.seed = int(sd["seed"])
        self.shuffle = bool(sd["shuffle"])
        self.epoch = int(sd["epoch"])
        self.cursor = int(sd["cursor"])
        self.batch_idx = int(sd["batch_idx"])
        self.samples_seen = int(sd["samples_seen"])
        self.quarantined = set(
            self._batch_id(q) for q in sd.get("quarantined", ()))
        self.last_delivered = None
        if not 0 <= self.cursor <= self.length:
            raise ValueError(
                f"data pipeline state cursor {self.cursor} outside "
                f"dataset of {self.length} samples")
        return self
