"""Double-buffered device prefetch.

NEW, TPU-first: the reference overlaps host→device copies with compute via
the C++ PrefetcherIter + a dedicated copy stream; on XLA the analog is
issuing ``jax.device_put`` for the NEXT batch(es) from a background thread
while the current step runs.  ``DevicePrefetcher`` wraps any iterable
batch source (gluon ``DataLoader``, ``io.DataIter``, a generator) and
keeps ``MXTPU_DEVICE_PREFETCH`` (default 2) batches in flight, already
placed on device — sharded over the data-parallel mesh axis when a mesh
is given, so multi-chip steps never reshard their inputs.

Every placement runs under ``profiler.annotate("h2d_prefetch")``: in an
xplane trace the transfer spans interleave with the step compute, which
is how the overlap is verified (docs/perf.md "Input pipeline").

``MXTPU_DEVICE_PREFETCH=0`` (or ``depth=0``) disables the background
thread entirely — batches are placed synchronously in the caller's
thread, restoring fully synchronous legacy behavior.

ID prefetch (PR 18): with ``sparse_tables=<block>`` the producer thread
also dedupes the NEXT batch's embedding ids per `ShardedEmbedding`
(`embedding.prep.prepare_one` — the dominant host cost of a captured
sparse step) and stashes the result for `gluon/captured.py` to consume
(`stash_prep`/`pop_prep`), so the unique/inverse work overlaps the
CURRENT step's device compute.  With ``kvstore=`` and ``warm_pull=
{key: out}`` it additionally issues `row_sparse_pull` for the next
batch's rows from the producer thread — cold-row fetch overlapped with
compute; the dist-kvstore push path (per-key ``bucketed_pushpull``,
compression residuals) is untouched.
"""

from __future__ import annotations

import os
import queue as _queue
import threading
import time as _time

import numpy as _np

from ... import profiler
from ... import telemetry
from ...ndarray.ndarray import NDArray, _from_jax


def default_depth():
    """Prefetch depth from MXTPU_DEVICE_PREFETCH (default 2 = double
    buffering: one batch on device waiting, one in transfer)."""
    try:
        return int(os.environ.get("MXTPU_DEVICE_PREFETCH", 2))
    except ValueError:
        return 2


def _sharding_for(arr, mesh, axis):
    """Batch-dim sharding over `axis` when divisible; replicated
    otherwise (ragged last batches must still place)."""
    from jax.sharding import NamedSharding, PartitionSpec

    n = mesh.shape.get(axis, 1)
    if arr.ndim >= 1 and n > 1 and arr.shape[0] % n == 0:
        return NamedSharding(mesh, PartitionSpec(axis))
    return NamedSharding(mesh, PartitionSpec())


def _place_leaf(leaf, mesh, axis):
    import jax

    raw = leaf._data if isinstance(leaf, NDArray) else leaf
    if isinstance(raw, (bytes, str)) or raw is None:
        return leaf
    if not (isinstance(raw, _np.ndarray) or hasattr(raw, "devices")):
        raw = _np.asarray(raw)
    if mesh is not None:
        placed = jax.device_put(raw, _sharding_for(raw, mesh, axis))
    else:
        placed = jax.device_put(raw)
    return _from_jax(placed)


def place(batch, mesh=None, axis="dp"):
    """Asynchronously place one batch's arrays on device (one
    ``jax.device_put`` per array), preserving structure.  Handles
    NDArray / numpy / jax leaves, (nested) lists and tuples, and
    ``io.DataBatch`` objects.  Non-array leaves pass through."""
    with profiler.annotate("h2d_prefetch"):
        return _place(batch, mesh, axis)


def _place(batch, mesh, axis):
    # leaves FIRST: probing .data on a numpy array can raise (the
    # memoryview property rejects extension dtypes like bfloat16)
    if isinstance(batch, (NDArray, _np.ndarray)) or hasattr(batch,
                                                            "devices"):
        return _place_leaf(batch, mesh, axis)
    if isinstance(batch, (list, tuple)):
        placed = [_place(b, mesh, axis) for b in batch]
        return tuple(placed) if isinstance(batch, tuple) else placed
    # io.DataBatch: place data/label lists, keep pad/index metadata
    if hasattr(batch, "data") and hasattr(batch, "label") \
            and hasattr(batch, "pad"):
        batch.data = [_place(d, mesh, axis) for d in batch.data] \
            if batch.data is not None else None
        batch.label = [_place(l, mesh, axis) for l in batch.label] \
            if batch.label is not None else None
        return batch
    return batch


def _batch_data(batch):
    """The data tensor of a (placed) batch: a bare array, the first
    element of a (data, label, ...) tuple/list, or ``DataBatch.data[0]``
    — mirroring what `Trainer.train_step` receives as ``data``."""
    if isinstance(batch, NDArray):
        return batch
    if isinstance(batch, (list, tuple)) and batch:
        return batch[0] if isinstance(batch[0], NDArray) else None
    d = getattr(batch, "data", None)
    if isinstance(d, (list, tuple)) and d and isinstance(d[0], NDArray):
        return d[0]
    return None


class _EndOfEpoch:
    pass


_END = _EndOfEpoch()


class DevicePrefetcher:
    """Wrap a batch source; deliver device-placed batches with overlap.

    Parameters
    ----------
    data : iterable
        DataLoader, DataIter, or any iterable of batches.  Re-iterated
        from scratch on every ``__iter__`` (call ``reset()`` between
        epochs for DataIter sources, as with the bare iterator).
    depth : int, optional
        Batches to keep in flight; default ``MXTPU_DEVICE_PREFETCH``
        (2).  ``0`` = synchronous placement, no background thread.
    mesh, axis :
        When given, batch arrays are placed with the data-parallel
        ``NamedSharding`` up front so the compiled step never reshards.
    sparse_tables : Block, optional
        A block tree containing `embedding.ShardedEmbedding` children:
        the producer thread computes each table's unique ids + inverse
        index for the batch it is about to yield and stashes them for
        the captured step (`embedding.prep`), overlapping the id prep
        with the current step's compute.
    kvstore, warm_pull :
        With a kvstore and ``warm_pull={key: out}``, the producer also
        issues ``row_sparse_pull(key, out, row_ids=<next batch's
        ids>)`` for every table whose parameter name matches ``key`` —
        the cold-row fetch overlaps compute instead of stalling the
        step.
    """

    def __init__(self, data, depth=None, mesh=None, axis="dp",
                 sparse_tables=None, kvstore=None, warm_pull=None):
        self._data = data
        self._depth = default_depth() if depth is None else int(depth)
        self._mesh = mesh
        self._axis = axis
        self._sparse_block = sparse_tables
        self._kvstore = kvstore
        self._warm_pull = dict(warm_pull or {})
        self._stop = None
        self._thread = None

    def _prep_sparse(self, placed):
        """Producer-side id prep for the batch about to be yielded:
        unique/inverse per sparse table (stashed for `pop_prep`) and the
        optional warm `row_sparse_pull` of the rows it will touch."""
        if self._sparse_block is None:
            return
        from ...embedding import prep as _prep

        data = _batch_data(placed)
        if data is None:
            return
        tables = _prep.find_sparse_embeddings(self._sparse_block)
        if not tables:
            return
        t0 = _time.perf_counter()
        preps = {}
        for pid, blk in tables.items():
            pr = _prep.prepare_one(data, blk)
            if pr is not None:
                preps[pid] = pr
            if self._kvstore is not None:
                dest = self._warm_pull.get(blk.weight.name)
                if dest is not None:
                    ids = pr.uniq[:pr.n_real] if pr is not None \
                        else _np.unique(_prep.extract_ids(
                            data, blk._feature, blk._input_dim))
                    self._kvstore.row_sparse_pull(
                        blk.weight.name, out=dest, row_ids=ids)
        if preps:
            _prep.stash_prep(data, preps)
        telemetry.count("input.id_prep_us",
                        int((_time.perf_counter() - t0) * 1e6))

    def __len__(self):
        return len(self._data)

    @property
    def batch_size(self):
        return getattr(self._data, "batch_size", None)

    @property
    def provide_data(self):
        return self._data.provide_data

    @property
    def provide_label(self):
        return self._data.provide_label

    def reset(self):
        """Stop any in-flight epoch and reset the wrapped source."""
        self._shutdown()
        if hasattr(self._data, "reset"):
            self._data.reset()

    def close(self):
        self._shutdown()

    def __del__(self):
        try:
            self._shutdown()
        except Exception:
            pass

    def _clear_prep(self):
        """Invalidate the PR 18 id-prefetch stash: preps computed for
        batches that will never be consumed (shutdown, restore,
        exception teardown) must not survive into the next epoch."""
        if self._sparse_block is None:
            return
        from ...embedding import prep as _prep

        _prep.clear_stash()

    def _shutdown(self):
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._stop = None
        self._thread = None
        self._clear_prep()

    # -- resumable pipeline state (gluon/data/state.py) ------------------------

    def state_dict(self):
        """The wrapped source's position.  Delivery-exact by
        construction: the source's cursor advances when *this* wrapper
        delivers a batch downstream, not when the producer thread
        prefetches it."""
        return self._data.state_dict()

    def load_state_dict(self, sd):
        """Restore never consumes a stale pre-crash batch: the producer
        thread is stopped and its in-flight (already-placed) batches
        and id-prep stash discarded BEFORE the source adopts the new
        cursor — the next ``__iter__`` re-fetches from the restored
        offset."""
        self._shutdown()
        self._data.load_state_dict(sd)
        return self

    def quarantine(self, batch_ids):
        """Delegate to the wrapped loader (see DataLoader.quarantine)."""
        return self._data.quarantine(batch_ids)

    def last_batch_id(self):
        """(epoch, batch_idx) of the last batch DELIVERED downstream
        (deferred accounting commits at the consumer side of the
        prefetch queue, so a batch the producer merely prefetched does
        not count), or None."""
        return self._data.last_batch_id()

    @property
    def samples_seen(self):
        return self._data.samples_seen

    def __iter__(self):
        self._shutdown()
        if self._depth <= 0:
            return self._sync_iter()
        return self._async_iter()

    def _sync_iter(self):
        it = iter(self._data)
        while True:
            # consumer-thread stall: fetching + placing the batch happens
            # inline, so the whole span is time the step loop waited
            t0 = _time.perf_counter()
            try:
                batch = next(it)
            except StopIteration:
                return
            placed = place(batch, self._mesh, self._axis)
            self._prep_sparse(placed)
            telemetry.count(
                "input.wait_us",
                int((_time.perf_counter() - t0) * 1e6))
            telemetry.count("input.batches")
            yield placed

    def _async_iter(self):
        q = _queue.Queue(maxsize=self._depth)
        stop = threading.Event()
        # resumable sources (seeded DataLoader): the producer runs ahead
        # of the training loop, so sample accounting is deferred — each
        # batch travels with its commit token and the state advances
        # only when the CONSUMER below delivers the batch downstream.
        # Tokens of batches a teardown discards are never committed.
        src = iter(self._data)
        acct = src if hasattr(src, "defer_accounting") else None
        if acct is not None:
            acct.defer_accounting()

        def producer():
            try:
                for batch in src:
                    placed = place(batch, self._mesh, self._axis)
                    self._prep_sparse(placed)
                    token = acct.take_token() if acct is not None \
                        else None
                    if not _put(q, stop, (placed, token)):
                        return
                token = acct.take_token() if acct is not None else None
                _put(q, stop, (_END, token))
            except BaseException as err:  # forwarded to the consumer
                _put(q, stop, err)

        t = threading.Thread(target=producer, daemon=True,
                             name="mxtpu-device-prefetch")
        self._stop, self._thread = stop, t
        t.start()
        try:
            while True:
                # consumer-thread stall: only the q.get wait counts — the
                # producer's place() overlaps compute and must not be
                # attributed to the step (it has its own h2d span)
                t0 = _time.perf_counter()
                while True:
                    try:
                        item = q.get(timeout=0.2)
                        break
                    except _queue.Empty:
                        if not t.is_alive() and q.empty():
                            return  # producer died without posting (rare)
                        continue
                telemetry.count(
                    "input.wait_us",
                    int((_time.perf_counter() - t0) * 1e6))
                if isinstance(item, BaseException):
                    raise item
                placed, token = item
                if acct is not None and token is not None:
                    acct.commit(token)   # delivery-time accounting
                if placed is _END:
                    return
                telemetry.count("input.batches")
                telemetry.gauge_set("input.queue_depth", q.qsize())
                yield placed
        finally:
            stop.set()
            while not q.empty():  # unblock a producer stuck on put
                try:
                    q.get_nowait()
                except _queue.Empty:
                    break
            t.join(timeout=5)
            # exception/abandon path: in-flight batches above were
            # discarded uncommitted; their stashed preps go with them
            self._clear_prep()
            if self._thread is t:
                self._stop, self._thread = None, None


def _put(q, stop, item):
    """Bounded put that gives up when the consumer abandoned the epoch."""
    while not stop.is_set():
        try:
            q.put(item, timeout=0.1)
            return True
        except _queue.Full:
            continue
    return False
