"""DataLoader.

Reference parity: python/mxnet/gluon/data/dataloader.py — DataLoader with
batchify (default_batchify_fn), samplers, and multi-worker loading.

TPU-first note: the reference uses multiprocessing workers with shared-memory
NDArrays.  Host-side decode/augment here uses a thread pool by default
(numpy/PIL release the GIL for the heavy parts, and threads avoid
re-importing jax per worker); ``thread_pool=False`` with num_workers>0 uses
processes with pickled numpy batches.
"""

from __future__ import annotations

import multiprocessing
import multiprocessing.pool
from concurrent.futures import ThreadPoolExecutor

import numpy as _np

from ...ndarray.ndarray import NDArray, _from_jax
from . import sampler as _sampler


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn)."""
    if isinstance(data[0], NDArray):
        import jax.numpy as jnp

        return _from_jax(jnp.stack([d._data for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    data = _np.asarray(data)
    import jax.numpy as jnp

    return _from_jax(jnp.asarray(data))


def default_mp_batchify_fn(data):
    """Batchify in a worker: keep numpy (cheap pickling), wrap in parent."""
    if isinstance(data[0], NDArray):
        return _np.stack([d.asnumpy() for d in data])
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_mp_batchify_fn(i) for i in data]
    return _np.asarray(data)


def _as_in_context(data, ctx):
    if isinstance(data, NDArray):
        return data.as_in_context(ctx)
    if isinstance(data, (list, tuple)):
        return [_as_in_context(d, ctx) for d in data]
    return data


class _Worker:
    """Picklable per-batch fetch closure for pool workers."""

    def __init__(self, dataset, batchify_fn):
        self._dataset = dataset
        self._batchify_fn = batchify_fn

    def __call__(self, samples):
        return self._batchify_fn([self._dataset[i] for i in samples])


class DataLoader:
    """Loads mini-batches from a Dataset (reference: gluon.data.DataLoader).

    Parameters follow the reference: dataset, batch_size, shuffle, sampler,
    last_batch ('keep'|'discard'|'rollover'), batch_sampler, batchify_fn,
    num_workers, pin_memory (ignored: XLA host buffers are already pinned),
    prefetch, thread_pool.
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._timeout = timeout

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                if shuffle:
                    sampler = _sampler.RandomSampler(len(dataset))
                else:
                    sampler = _sampler.SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = _sampler.BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")

        self._batch_sampler = batch_sampler
        self._num_workers = num_workers if num_workers >= 0 else 0
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        if batchify_fn is None:
            if num_workers > 0 and not thread_pool:
                self._batchify_fn = default_mp_batchify_fn
            else:
                self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn

    def __iter__(self):
        if self._num_workers == 0:
            def same_process_iter():
                for batch in self._batch_sampler:
                    ret = self._batchify_fn(
                        [self._dataset[idx] for idx in batch])
                    yield ret
            return same_process_iter()
        return _MultiWorkerIter(self)

    def __len__(self):
        return len(self._batch_sampler)


class _MultiWorkerIter:
    """Pool-based prefetching iterator."""

    def __init__(self, loader):
        self._loader = loader
        self._worker = _Worker(loader._dataset, loader._batchify_fn)
        if loader._thread_pool:
            self._pool = ThreadPoolExecutor(
                max_workers=loader._num_workers)
            self._submit = self._pool.submit
        else:
            self._mp_pool = multiprocessing.get_context("spawn").Pool(
                loader._num_workers)
            self._submit = lambda fn, arg: self._mp_pool.apply_async(fn,
                                                                     (arg,))
        self._batches = iter(loader._batch_sampler)
        self._pending = []
        self._done = False
        for _ in range(max(1, loader._prefetch)):
            self._push_next()

    def _push_next(self):
        batch = next(self._batches, None)
        if batch is None:
            return
        self._pending.append(self._submit(self._worker, batch))

    def __iter__(self):
        return self

    def __next__(self):
        if not self._pending:
            self._shutdown()
            raise StopIteration
        fut = self._pending.pop(0)
        self._push_next()
        if hasattr(fut, "result"):
            out = fut.result(timeout=self._loader._timeout)
        else:
            out = fut.get(timeout=self._loader._timeout)
        if isinstance(out, _np.ndarray) or (
                isinstance(out, list)
                and out and isinstance(out[0], _np.ndarray)):
            # mp path returns numpy; wrap on the parent process
            import jax.numpy as jnp

            if isinstance(out, list):
                return [_from_jax(jnp.asarray(o)) for o in out]
            return _from_jax(jnp.asarray(out))
        return out

    def _shutdown(self):
        if hasattr(self, "_pool"):
            self._pool.shutdown(wait=False)
        if hasattr(self, "_mp_pool"):
            self._mp_pool.terminate()
