"""DataLoader.

Reference parity: python/mxnet/gluon/data/dataloader.py — DataLoader with
batchify (default_batchify_fn), samplers, and multi-worker loading.

TPU-first notes:

- **Single-copy collation**: ``default_batchify_fn`` collates samples into
  one preallocated contiguous host buffer and issues exactly ONE async
  ``jax.device_put`` per batch array — no per-sample host→device
  transfers, no device-side ``jnp.stack`` (the pre-round-3 path issued
  one transfer per *sample*; see docs/perf.md "Input pipeline").
- **Workers**: host-side decode/augment uses a thread pool by default
  (numpy/PIL release the GIL for the heavy parts, and threads avoid
  re-importing jax per worker); ``thread_pool=False`` with num_workers>0
  spawns processes that transport batches through shared-memory ring
  slots (``_shm_worker.py``) instead of pickling, with out-of-order
  completion and in-order delivery — a slow worker delays only its own
  batch.  ``MXTPU_SHM_SLOT_MB`` sizes the ring slots; oversized batches
  fall back to pickle transport transparently.
- Device placement overlap lives one layer up: wrap any loader in
  ``mxnet_tpu.gluon.data.DevicePrefetcher`` (prefetcher.py).
"""

from __future__ import annotations

import collections
import multiprocessing
import os
import queue as _queue
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as _FutTimeout

import numpy as _np

from ... import resilience as _resilience
from ... import telemetry as _telemetry
from ...ndarray.ndarray import NDArray, _from_jax
from . import sampler as _sampler
from . import _shm_worker
from .state import DataPipelineState


class DataLoaderWorkerError(RuntimeError):
    """A dataset ``__getitem__``/batchify raised inside a loader worker.

    Carries the failing batch's sample indices and (for process workers)
    the worker-side traceback, instead of the opaque pickling/timeout
    error the raw transport would produce."""


def _on_host(nd):
    """True when an NDArray's buffer lives on the host platform (so a
    per-sample ``asnumpy`` is a cheap view/copy, not a device readback)."""
    try:
        return next(iter(nd._data.devices())).platform == "cpu"
    except Exception:
        return True


def _wrap_device(collated):
    """One async ``jax.device_put`` per collated batch array."""
    if isinstance(collated, list):
        return [_wrap_device(c) for c in collated]
    import jax

    return _from_jax(jax.device_put(collated))


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn).

    Collates on the host into one contiguous buffer per output array and
    performs a single async device transfer per array."""
    if isinstance(data[0], NDArray) and not _on_host(data[0]):
        # device-resident samples: stacking on-device beats a readback
        import jax.numpy as jnp

        return _from_jax(jnp.stack([d._data for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    return _wrap_device(_shm_worker.collate_column(data))


def default_mp_batchify_fn(data):
    """Batchify in a worker: keep numpy (single-copy collation into a
    contiguous buffer); the parent wraps with one device_put per array."""
    return _shm_worker.collate_samples(data)


def _as_in_context(data, ctx):
    if isinstance(data, NDArray):
        return data.as_in_context(ctx)
    if isinstance(data, (list, tuple)):
        return [_as_in_context(d, ctx) for d in data]
    return data


class _Worker:
    """Picklable per-batch fetch closure for pool workers."""

    def __init__(self, dataset, batchify_fn):
        self._dataset = dataset
        self._batchify_fn = batchify_fn

    def __call__(self, samples, batch_idx=None):
        if batch_idx is not None:
            # worker_hang:K / data_skew:K fault sites (thread transport;
            # _shm_worker mirrors this for spawn workers)
            _resilience.maybe_data_fault(batch_idx)
        return self._batchify_fn([self._dataset[i] for i in samples])


class DataLoader:
    """Loads mini-batches from a Dataset (reference: gluon.data.DataLoader).

    Parameters follow the reference: dataset, batch_size, shuffle, sampler,
    last_batch ('keep'|'discard'|'rollover'), batch_sampler, batchify_fn,
    num_workers, pin_memory (ignored: XLA host buffers are already pinned),
    prefetch (None -> 2*num_workers; 0 -> at most one batch in flight),
    thread_pool.

    TPU-first additions (exactly-once resumable pipeline, see
    ``gluon/data/state.py``):

    - ``seed``: opting in makes the loader **resumable** — the sample
      order becomes a pure function of ``(seed, epoch)``, the loader
      exposes ``state_dict()/load_state_dict()`` (epoch, global sample
      cursor, quarantined batches) for the checkpoint path, and replay
      after a `DivergenceMonitor` rollback skips quarantined batches
      with one ``batch_quarantined`` telemetry event each.
    - ``rank``/``world_size``: this loader's slice of the global order
      (``order[cursor:][rank::world]``).  A restored state keeps the
      LOCAL rank/world, so an elastic N→M reshape re-shards the
      remaining epoch deterministically with zero re-read and zero
      skipped samples.
    - ``MXTPU_DATA_TIMEOUT`` (seconds, default = ``timeout``): receive
      watchdog for worker batches — a hung worker raises
      `DataLoaderWorkerError` naming the batch instead of blocking the
      training step past the gang's heartbeat window.
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120,
                 seed=None, rank=0, world_size=1):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._timeout = timeout
        self._state = None

        if seed is not None:
            if batch_sampler is not None or sampler is not None:
                raise ValueError(
                    "seed= (resumable loading) builds its own sampler; "
                    "it cannot be combined with sampler= or "
                    "batch_sampler=")
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified with seed=")
            self._state = DataPipelineState(
                len(dataset), seed=seed, shuffle=shuffle,
                rank=rank, world=world_size)
            sampler = _sampler.ResumableSampler(self._state)
            shuffle = False   # the ResumableSampler owns the order

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                if shuffle:
                    sampler = _sampler.RandomSampler(len(dataset))
                else:
                    sampler = _sampler.SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = _sampler.BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")

        self._batch_sampler = batch_sampler
        self._num_workers = num_workers if num_workers >= 0 else 0
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        self._custom_batchify = batchify_fn is not None
        if batchify_fn is None:
            if num_workers > 0 and not thread_pool:
                self._batchify_fn = default_mp_batchify_fn
            else:
                self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn

    def __iter__(self):
        if self._state is None:
            return self._raw_iter(iter(self._batch_sampler))
        return _ResumableIter(self)

    def _raw_iter(self, batches):
        """The transport-level iterator over an index-batch stream."""
        if self._num_workers == 0:
            def same_process_iter():
                for batch in batches:
                    ret = self._batchify_fn(
                        [self._dataset[idx] for idx in batch])
                    yield ret
            return same_process_iter()
        return _MultiWorkerIter(self, batches)

    def __len__(self):
        return len(self._batch_sampler)

    # -- resumable pipeline state (gluon/data/state.py) ------------------------

    def _require_state(self, what):
        if self._state is None:
            raise RuntimeError(
                f"DataLoader.{what}: construct the loader with seed= to "
                f"make it resumable")
        return self._state

    def state_dict(self):
        """JSON-serializable pipeline position (delivery-exact: never
        counts prefetched-but-undelivered batches)."""
        return self._require_state("state_dict").state_dict()

    def load_state_dict(self, sd):
        """Adopt a checkpointed position; the next ``__iter__`` resumes
        at the exact sample offset (zero re-read, zero skipped).  The
        loader's own rank/world are kept — loading an N-rank state into
        an M-rank loader IS the elastic re-shard."""
        st = self._require_state("load_state_dict")
        st.load_state_dict(sd)
        _telemetry.event("data_resume", epoch=st.epoch, cursor=st.cursor,
                         samples_seen=st.samples_seen,
                         reread_samples=0, skipped_samples=0,
                         world=st.world, loader_rank=st.rank)
        return self

    def quarantine(self, batch_ids):
        """Mark ``(epoch, batch_idx)`` batch ids to be skipped (loudly)
        on replay — the `DivergenceMonitor` rollback hookup."""
        self._require_state("quarantine").quarantine(batch_ids)

    def last_batch_id(self):
        """``(epoch, batch_idx)`` of the newest delivered batch (what
        the Trainer reports to `DivergenceMonitor.observe`)."""
        return self._require_state("last_batch_id").last_delivered

    @property
    def samples_seen(self):
        return self._require_state("samples_seen").samples_seen


def _slot_bytes():
    return int(float(os.environ.get("MXTPU_SHM_SLOT_MB", 32)) * (1 << 20))


class _MultiWorkerIter:
    """Prefetching iterator over pool workers.

    Thread pool: futures are delivered in submit order; the executor runs
    them concurrently.  Process pool: workers pull from a shared task
    queue (out-of-order completion), results are reordered in the parent
    so delivery matches the sampler order — identical batches, identical
    order, regardless of transport.

    The iterator owns OS resources; it cleans up on exhaustion, on
    ``close()``, on ``__del__`` (abandoned mid-epoch), and supports use
    as a context manager.
    """

    def __init__(self, loader, batches=None):
        self._loader = loader
        self._batches = iter(loader._batch_sampler) if batches is None \
            else iter(batches)
        # receive watchdog: how long a delivery may wait on one worker
        # result before declaring it hung (default: the transport
        # timeout) — keeps a wedged worker from stalling step_tick past
        # the gang's heartbeat window
        self._data_timeout = float(
            os.environ.get("MXTPU_DATA_TIMEOUT", loader._timeout))
        self._depth = max(1, loader._prefetch)
        self._sent_idx = 0
        self._rcvd_idx = 0
        self._data_buffer = {}  # batch_idx -> result record
        self._closed = False
        self._pool = None
        self._procs = []
        if loader._thread_pool:
            self._worker = _Worker(loader._dataset, loader._batchify_fn)
            self._pool = ThreadPoolExecutor(max_workers=loader._num_workers)
        else:
            self._start_processes(loader)
        for _ in range(self._depth):
            self._push_next()

    # -- process transport -----------------------------------------------------

    def _start_processes(self, loader):
        ctx = multiprocessing.get_context("spawn")
        nslots = max(self._depth, loader._num_workers)
        self._slots = [ctx.RawArray("b", _slot_bytes())
                       for _ in range(nslots)]
        self._free_slots = list(range(nslots))
        self._task_q = ctx.Queue()
        self._result_q = ctx.Queue()
        # None selects the jax-free built-in collation in the worker; a
        # pickled reference to the default fn would drag the whole
        # package (and jax) into every spawned child
        fn = loader._batchify_fn if loader._custom_batchify else None
        for _ in range(loader._num_workers):
            p = ctx.Process(
                target=_shm_worker.worker_loop,
                args=(loader._dataset, fn, self._slots, self._task_q,
                      self._result_q),
                daemon=True)
            p.start()
            self._procs.append(p)

    def _push_next(self):
        if self._closed:
            return
        if self._pool is None and not self._free_slots:
            return  # every ring slot is in flight
        batch = next(self._batches, None)
        if batch is None:
            return
        if self._pool is not None:
            fut = self._pool.submit(self._worker, batch, self._sent_idx)
            self._data_buffer[self._sent_idx] = ("future", fut, batch)
        else:
            slot = self._free_slots.pop()
            self._task_q.put((self._sent_idx, slot, list(batch)))
        self._sent_idx += 1

    def _recv_until(self, idx):
        """Drain the result queue until batch `idx` has arrived.

        Slots are copied out and recycled at *receive* time, not delivery
        time, so an out-of-order fast batch never pins a slot while a
        slow one is pending."""
        while idx not in self._data_buffer:
            try:
                msg = self._result_q.get(timeout=self._data_timeout)
            except _queue.Empty:
                alive = [p.pid for p in self._procs if p.is_alive()]
                self.close(wait=False)
                self._note_timeout(idx)
                raise DataLoaderWorkerError(
                    f"DataLoader worker result for batch {idx} not "
                    f"received within MXTPU_DATA_TIMEOUT="
                    f"{self._data_timeout}s (hung worker? live worker "
                    f"pids: {alive})")
            tag, bidx, slot, payload, is_list = msg
            if tag == "shm":
                out = _shm_worker.read_slot(self._slots[slot], payload,
                                            is_list)
                self._data_buffer[bidx] = ("data", out, None)
            elif tag == "pickle":
                self._data_buffer[bidx] = ("data", payload, None)
            else:  # "error"
                self._data_buffer[bidx] = ("error", payload, None)
            if slot is not None:
                self._free_slots.append(slot)
                self._push_next()

    # -- iteration -------------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._rcvd_idx == self._sent_idx or self._closed:
            self.close()
            raise StopIteration
        idx = self._rcvd_idx
        if self._pool is not None:
            kind, fut, samples = self._data_buffer.pop(idx)
            self._rcvd_idx += 1
            self._push_next()
            try:
                out = fut.result(timeout=self._data_timeout)
            except _FutTimeout as err:
                self.close(wait=False)
                self._note_timeout(idx)
                raise DataLoaderWorkerError(
                    f"DataLoader worker thread hung on batch {idx} "
                    f"(sample indices {list(samples)}): no result "
                    f"within MXTPU_DATA_TIMEOUT="
                    f"{self._data_timeout}s") from err
            except Exception as err:
                self.close()
                raise DataLoaderWorkerError(
                    f"DataLoader worker failed on batch {idx} (sample "
                    f"indices {list(samples)}): {err!r}") from err
        else:
            self._recv_until(idx)
            kind, out, _ = self._data_buffer.pop(idx)
            self._rcvd_idx += 1
            if kind == "error":
                exc_repr, tb, samples = out
                self.close()
                raise DataLoaderWorkerError(
                    f"DataLoader worker failed on batch {idx} (sample "
                    f"indices {samples}): {exc_repr}\n"
                    f"--- worker traceback ---\n{tb}")
        if isinstance(out, _np.ndarray) or (
                isinstance(out, list)
                and out and isinstance(out[0], _np.ndarray)):
            # worker transports host numpy; one device_put per array here
            return _wrap_device(out)
        return out

    # -- cleanup ---------------------------------------------------------------

    @staticmethod
    def _note_timeout(idx):
        _telemetry.event("data_worker_timeout", batch=int(idx))

    def close(self, wait=True):
        """Cancel pending work and release threads/processes/queues.
        ``wait=False`` (the hung-worker watchdog path) skips blocking
        joins — waiting on the very worker that just timed out would
        turn the watchdog into the hang it exists to break."""
        if self._closed:
            return
        self._closed = True
        if self._pool is not None:
            self._pool.shutdown(wait=wait, cancel_futures=True)
        for _ in self._procs:
            try:
                self._task_q.put(None)
            except (ValueError, OSError):
                pass
        for p in self._procs:
            p.join(timeout=5 if wait else 0.1)
            if p.is_alive():
                p.terminate()
                p.join(timeout=1)
        if self._procs:
            for q in (self._task_q, self._result_q):
                q.cancel_join_thread()
                q.close()
        self._data_buffer.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class _ResumableIter:
    """Delivery-time sample accounting + quarantine-honoring replay.

    Wraps the transport iterator (same-process generator or
    `_MultiWorkerIter`) of a seeded DataLoader.  The batch *plan* — the
    submission-ordered stream of index batches — is generated lazily
    from the `ResumableSampler` and tagged with each batch's global
    ordinal; quarantined ordinals are dropped from the plan (never
    fetched — a poisoned batch must not be decoded, let alone trained
    on).  The shared `DataPipelineState` advances only when a batch is
    actually DELIVERED here (prefetched-but-undelivered work is
    invisible to a checkpoint), with any preceding quarantine skips
    accounted — and announced via one ``batch_quarantined`` telemetry
    event each — in exact delivery order.

    A wrapper that prefetches FURTHER downstream (`DevicePrefetcher`)
    calls ``defer_accounting()``: each delivery then queues a commit
    *token* instead of applying it, and the wrapper commits the token
    when the batch finally reaches ITS consumer — so the state is
    delivery-exact at the outermost layer, and tokens for batches a
    teardown discards are simply never committed.
    """

    def __init__(self, loader):
        self._loader = loader
        self._state = loader._state
        # submission-ordered ("skip"|"deliver", ordinal, n_samples)
        # events, drained in delivery order by _drain()
        self._events = collections.deque()
        self._inner = loader._raw_iter(self._plan())
        self._done = False
        self._deferred = False
        self._tokens = collections.deque()

    def _plan(self):
        st = self._state
        epoch, ordinal = st.epoch, st.batch_idx
        for batch in self._loader._batch_sampler:
            quarantined = st.is_quarantined(epoch, ordinal)
            self._events.append(
                ("skip" if quarantined else "deliver", ordinal,
                 len(batch)))
            ordinal += 1
            if not quarantined:
                yield batch

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        try:
            out = next(self._inner)
        except StopIteration:
            self._finish_epoch()
            raise
        self._settle(self._drain(stop_after_deliver=True))
        return out

    def _drain(self, stop_after_deliver):
        token = []
        while self._events:
            ev = self._events.popleft()
            token.append(ev)
            if stop_after_deliver and ev[0] == "deliver":
                break
        return token

    def _settle(self, token):
        if self._deferred:
            self._tokens.append(token)
        else:
            self.commit(token)

    def _finish_epoch(self):
        # trailing events can only be quarantine skips (every deliver
        # event precedes its batch's delivery)
        token = self._drain(stop_after_deliver=False)
        token.append(("epoch_end",))
        self._settle(token)
        self._done = True

    # -- deferred accounting (DevicePrefetcher) --------------------------------

    def defer_accounting(self):
        """Queue commit tokens instead of applying them: the caller is
        prefetching ahead of the real consumer and will ``commit`` each
        token at downstream delivery time."""
        self._deferred = True
        return self

    def take_token(self):
        return self._tokens.popleft() if self._tokens else None

    def commit(self, token):
        st = self._state
        for ev in token or ():
            if ev[0] == "skip":
                _, ordinal, n = ev
                st.skip(n)
                _telemetry.event("batch_quarantined", epoch=st.epoch,
                                 batch=int(ordinal), samples=int(n))
            elif ev[0] == "deliver":
                st.advance(ev[2])
            else:   # "epoch_end"
                st.next_epoch()

    def close(self):
        close = getattr(self._inner, "close", None)
        if close is not None:
            close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
