"""DataLoader.

Reference parity: python/mxnet/gluon/data/dataloader.py — DataLoader with
batchify (default_batchify_fn), samplers, and multi-worker loading.

TPU-first notes:

- **Single-copy collation**: ``default_batchify_fn`` collates samples into
  one preallocated contiguous host buffer and issues exactly ONE async
  ``jax.device_put`` per batch array — no per-sample host→device
  transfers, no device-side ``jnp.stack`` (the pre-round-3 path issued
  one transfer per *sample*; see docs/perf.md "Input pipeline").
- **Workers**: host-side decode/augment uses a thread pool by default
  (numpy/PIL release the GIL for the heavy parts, and threads avoid
  re-importing jax per worker); ``thread_pool=False`` with num_workers>0
  spawns processes that transport batches through shared-memory ring
  slots (``_shm_worker.py``) instead of pickling, with out-of-order
  completion and in-order delivery — a slow worker delays only its own
  batch.  ``MXTPU_SHM_SLOT_MB`` sizes the ring slots; oversized batches
  fall back to pickle transport transparently.
- Device placement overlap lives one layer up: wrap any loader in
  ``mxnet_tpu.gluon.data.DevicePrefetcher`` (prefetcher.py).
"""

from __future__ import annotations

import multiprocessing
import os
import queue as _queue
from concurrent.futures import ThreadPoolExecutor

import numpy as _np

from ...ndarray.ndarray import NDArray, _from_jax
from . import sampler as _sampler
from . import _shm_worker


class DataLoaderWorkerError(RuntimeError):
    """A dataset ``__getitem__``/batchify raised inside a loader worker.

    Carries the failing batch's sample indices and (for process workers)
    the worker-side traceback, instead of the opaque pickling/timeout
    error the raw transport would produce."""


def _on_host(nd):
    """True when an NDArray's buffer lives on the host platform (so a
    per-sample ``asnumpy`` is a cheap view/copy, not a device readback)."""
    try:
        return next(iter(nd._data.devices())).platform == "cpu"
    except Exception:
        return True


def _wrap_device(collated):
    """One async ``jax.device_put`` per collated batch array."""
    if isinstance(collated, list):
        return [_wrap_device(c) for c in collated]
    import jax

    return _from_jax(jax.device_put(collated))


def default_batchify_fn(data):
    """Stack samples into a batch (reference: default_batchify_fn).

    Collates on the host into one contiguous buffer per output array and
    performs a single async device transfer per array."""
    if isinstance(data[0], NDArray) and not _on_host(data[0]):
        # device-resident samples: stacking on-device beats a readback
        import jax.numpy as jnp

        return _from_jax(jnp.stack([d._data for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    return _wrap_device(_shm_worker.collate_column(data))


def default_mp_batchify_fn(data):
    """Batchify in a worker: keep numpy (single-copy collation into a
    contiguous buffer); the parent wraps with one device_put per array."""
    return _shm_worker.collate_samples(data)


def _as_in_context(data, ctx):
    if isinstance(data, NDArray):
        return data.as_in_context(ctx)
    if isinstance(data, (list, tuple)):
        return [_as_in_context(d, ctx) for d in data]
    return data


class _Worker:
    """Picklable per-batch fetch closure for pool workers."""

    def __init__(self, dataset, batchify_fn):
        self._dataset = dataset
        self._batchify_fn = batchify_fn

    def __call__(self, samples):
        return self._batchify_fn([self._dataset[i] for i in samples])


class DataLoader:
    """Loads mini-batches from a Dataset (reference: gluon.data.DataLoader).

    Parameters follow the reference: dataset, batch_size, shuffle, sampler,
    last_batch ('keep'|'discard'|'rollover'), batch_sampler, batchify_fn,
    num_workers, pin_memory (ignored: XLA host buffers are already pinned),
    prefetch (None -> 2*num_workers; 0 -> at most one batch in flight),
    thread_pool.
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=True, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._thread_pool = thread_pool
        self._timeout = timeout

        if batch_sampler is None:
            if batch_size is None:
                raise ValueError(
                    "batch_size must be specified unless batch_sampler is "
                    "specified")
            if sampler is None:
                if shuffle:
                    sampler = _sampler.RandomSampler(len(dataset))
                else:
                    sampler = _sampler.SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError(
                    "shuffle must not be specified if sampler is specified")
            batch_sampler = _sampler.BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError(
                "batch_size, shuffle, sampler and last_batch must not be "
                "specified if batch_sampler is specified.")

        self._batch_sampler = batch_sampler
        self._num_workers = num_workers if num_workers >= 0 else 0
        self._prefetch = max(0, int(prefetch) if prefetch is not None
                             else 2 * self._num_workers)
        self._custom_batchify = batchify_fn is not None
        if batchify_fn is None:
            if num_workers > 0 and not thread_pool:
                self._batchify_fn = default_mp_batchify_fn
            else:
                self._batchify_fn = default_batchify_fn
        else:
            self._batchify_fn = batchify_fn

    def __iter__(self):
        if self._num_workers == 0:
            def same_process_iter():
                for batch in self._batch_sampler:
                    ret = self._batchify_fn(
                        [self._dataset[idx] for idx in batch])
                    yield ret
            return same_process_iter()
        return _MultiWorkerIter(self)

    def __len__(self):
        return len(self._batch_sampler)


def _slot_bytes():
    return int(float(os.environ.get("MXTPU_SHM_SLOT_MB", 32)) * (1 << 20))


class _MultiWorkerIter:
    """Prefetching iterator over pool workers.

    Thread pool: futures are delivered in submit order; the executor runs
    them concurrently.  Process pool: workers pull from a shared task
    queue (out-of-order completion), results are reordered in the parent
    so delivery matches the sampler order — identical batches, identical
    order, regardless of transport.

    The iterator owns OS resources; it cleans up on exhaustion, on
    ``close()``, on ``__del__`` (abandoned mid-epoch), and supports use
    as a context manager.
    """

    def __init__(self, loader):
        self._loader = loader
        self._batches = iter(loader._batch_sampler)
        self._depth = max(1, loader._prefetch)
        self._sent_idx = 0
        self._rcvd_idx = 0
        self._data_buffer = {}  # batch_idx -> result record
        self._closed = False
        self._pool = None
        self._procs = []
        if loader._thread_pool:
            self._worker = _Worker(loader._dataset, loader._batchify_fn)
            self._pool = ThreadPoolExecutor(max_workers=loader._num_workers)
        else:
            self._start_processes(loader)
        for _ in range(self._depth):
            self._push_next()

    # -- process transport -----------------------------------------------------

    def _start_processes(self, loader):
        ctx = multiprocessing.get_context("spawn")
        nslots = max(self._depth, loader._num_workers)
        self._slots = [ctx.RawArray("b", _slot_bytes())
                       for _ in range(nslots)]
        self._free_slots = list(range(nslots))
        self._task_q = ctx.Queue()
        self._result_q = ctx.Queue()
        # None selects the jax-free built-in collation in the worker; a
        # pickled reference to the default fn would drag the whole
        # package (and jax) into every spawned child
        fn = loader._batchify_fn if loader._custom_batchify else None
        for _ in range(loader._num_workers):
            p = ctx.Process(
                target=_shm_worker.worker_loop,
                args=(loader._dataset, fn, self._slots, self._task_q,
                      self._result_q),
                daemon=True)
            p.start()
            self._procs.append(p)

    def _push_next(self):
        if self._closed:
            return
        if self._pool is None and not self._free_slots:
            return  # every ring slot is in flight
        batch = next(self._batches, None)
        if batch is None:
            return
        if self._pool is not None:
            fut = self._pool.submit(self._worker, batch)
            self._data_buffer[self._sent_idx] = ("future", fut, batch)
        else:
            slot = self._free_slots.pop()
            self._task_q.put((self._sent_idx, slot, list(batch)))
        self._sent_idx += 1

    def _recv_until(self, idx):
        """Drain the result queue until batch `idx` has arrived.

        Slots are copied out and recycled at *receive* time, not delivery
        time, so an out-of-order fast batch never pins a slot while a
        slow one is pending."""
        while idx not in self._data_buffer:
            try:
                msg = self._result_q.get(timeout=self._loader._timeout)
            except _queue.Empty:
                self.close()
                raise DataLoaderWorkerError(
                    f"DataLoader worker result for batch {idx} not "
                    f"received within timeout={self._loader._timeout}s")
            tag, bidx, slot, payload, is_list = msg
            if tag == "shm":
                out = _shm_worker.read_slot(self._slots[slot], payload,
                                            is_list)
                self._data_buffer[bidx] = ("data", out, None)
            elif tag == "pickle":
                self._data_buffer[bidx] = ("data", payload, None)
            else:  # "error"
                self._data_buffer[bidx] = ("error", payload, None)
            if slot is not None:
                self._free_slots.append(slot)
                self._push_next()

    # -- iteration -------------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._rcvd_idx == self._sent_idx or self._closed:
            self.close()
            raise StopIteration
        idx = self._rcvd_idx
        if self._pool is not None:
            kind, fut, samples = self._data_buffer.pop(idx)
            self._rcvd_idx += 1
            self._push_next()
            try:
                out = fut.result(timeout=self._loader._timeout)
            except Exception as err:
                self.close()
                raise DataLoaderWorkerError(
                    f"DataLoader worker failed on batch {idx} (sample "
                    f"indices {list(samples)}): {err!r}") from err
        else:
            self._recv_until(idx)
            kind, out, _ = self._data_buffer.pop(idx)
            self._rcvd_idx += 1
            if kind == "error":
                exc_repr, tb, samples = out
                self.close()
                raise DataLoaderWorkerError(
                    f"DataLoader worker failed on batch {idx} (sample "
                    f"indices {samples}): {exc_repr}\n"
                    f"--- worker traceback ---\n{tb}")
        if isinstance(out, _np.ndarray) or (
                isinstance(out, list)
                and out and isinstance(out[0], _np.ndarray)):
            # worker transports host numpy; one device_put per array here
            return _wrap_device(out)
        return out

    # -- cleanup ---------------------------------------------------------------

    def close(self):
        """Cancel pending work and release threads/processes/queues."""
        if self._closed:
            return
        self._closed = True
        if self._pool is not None:
            self._pool.shutdown(wait=True, cancel_futures=True)
        for _ in self._procs:
            try:
                self._task_q.put(None)
            except (ValueError, OSError):
                pass
        for p in self._procs:
            p.join(timeout=5)
            if p.is_alive():
                p.terminate()
                p.join(timeout=1)
        if self._procs:
            for q in (self._task_q, self._result_q):
                q.cancel_join_thread()
                q.close()
        self._data_buffer.clear()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
