"""Gluon data API (reference: python/mxnet/gluon/data/)."""

from .dataset import (ArrayDataset, Dataset, RecordFileDataset,
                      SimpleDataset)
from .sampler import (BatchSampler, FilterSampler, RandomSampler,
                      ResumableSampler, Sampler, SequentialSampler)
from .dataloader import (DataLoader, DataLoaderWorkerError,
                         default_batchify_fn, default_mp_batchify_fn)
from .prefetcher import DevicePrefetcher
from .state import DataPipelineState, epoch_order
from . import vision
