"""Vision datasets.

Reference parity: python/mxnet/gluon/data/vision/datasets.py — MNIST,
FashionMNIST, CIFAR10, CIFAR100, ImageRecordDataset, ImageFolderDataset.

Zero-egress environment: datasets read from ``root`` if the standard files
are present and raise a clear error otherwise (the reference would
download).
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as _np

from ....base import MXNetError
from ...block import Block  # noqa: F401  (parity import)
from ..dataset import ArrayDataset, Dataset, RecordFileDataset
from ....ndarray.ndarray import _from_jax


def _to_nd(arr):
    import jax.numpy as jnp

    return _from_jax(jnp.asarray(arr))


class _DownloadedDataset(Dataset):
    def __init__(self, root, transform):
        super().__init__()
        self._transform = transform
        self._data = None
        self._label = None
        root = os.path.expanduser(root)
        self._root = root
        if not os.path.isdir(root):
            os.makedirs(root, exist_ok=True)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST (reference: gluon.data.vision.MNIST); expects the idx files
    under root."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        self._train = train
        super().__init__(root, transform)

    def _open(self, name):
        path = os.path.join(self._root, name)
        if os.path.exists(path):
            return open(path, "rb")
        if os.path.exists(path + ".gz"):
            return gzip.open(path + ".gz", "rb")
        raise MXNetError(
            f"MNIST file {name} not found under {self._root} and this "
            "environment has no network access. Place the idx files there "
            "manually.")

    def _get_data(self):
        image_file, label_file = self._train_files if self._train \
            else self._test_files
        with self._open(label_file) as fin:
            struct.unpack(">II", fin.read(8))
            label = _np.frombuffer(fin.read(), dtype=_np.uint8) \
                .astype(_np.int32)
        with self._open(image_file) as fin:
            struct.unpack(">IIII", fin.read(16))
            data = _np.frombuffer(fin.read(), dtype=_np.uint8)
            data = data.reshape(len(label), 28, 28, 1)
        self._data = _to_nd(data)
        self._label = label


class FashionMNIST(MNIST):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"),
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR10 (reference: gluon.data.vision.CIFAR10); expects the python
    pickle batches or the binary batches under root."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar10"),
                 train=True, transform=None):
        self._train = train
        self._archive_file_name = "cifar-10-binary.tar.gz"
        super().__init__(root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = _np.frombuffer(fin.read(), dtype=_np.uint8).reshape(
                -1, 3072 + 1)
        return data[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0].astype(_np.int32)

    def _get_data(self):
        if self._train:
            filename = [os.path.join(self._root,
                                     f"data_batch_{i + 1}.bin")
                        for i in range(5)]
        else:
            filename = [os.path.join(self._root, "test_batch.bin")]
        missing = [f for f in filename if not os.path.exists(f)]
        if missing:
            archive = os.path.join(self._root, self._archive_file_name)
            if os.path.exists(archive):
                with tarfile.open(archive) as tar:
                    tar.extractall(self._root)
                # binary batches live in a subdir
                sub = os.path.join(self._root, "cifar-10-batches-bin")
                if os.path.isdir(sub):
                    for f in os.listdir(sub):
                        os.replace(os.path.join(sub, f),
                                   os.path.join(self._root, f))
            missing = [f for f in filename if not os.path.exists(f)]
        if missing:
            raise MXNetError(
                f"CIFAR10 files {missing} not found and this environment "
                "has no network access.")
        data, label = zip(*[self._read_batch(f) for f in filename])
        self._data = _to_nd(_np.concatenate(data))
        self._label = _np.concatenate(label)


class CIFAR100(CIFAR10):
    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"),
                 fine_label=False, train=True, transform=None):
        self._fine_label = fine_label
        self._train = train
        self._archive_file_name = "cifar-100-binary.tar.gz"
        _DownloadedDataset.__init__(self, root, transform)

    def _read_batch(self, filename):
        with open(filename, "rb") as fin:
            data = _np.frombuffer(fin.read(), dtype=_np.uint8).reshape(
                -1, 3072 + 2)
        return data[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1), \
            data[:, 0 + self._fine_label].astype(_np.int32)

    def _get_data(self):
        filename = [os.path.join(self._root,
                                 "train.bin" if self._train else "test.bin")]
        missing = [f for f in filename if not os.path.exists(f)]
        if missing:
            raise MXNetError(
                f"CIFAR100 files {missing} not found and this environment "
                "has no network access.")
        data, label = zip(*[self._read_batch(f) for f in filename])
        self._data = _to_nd(_np.concatenate(data))
        self._label = _np.concatenate(label)


class ImageRecordDataset(RecordFileDataset):
    """Images + labels from a .rec file (reference:
    gluon.data.vision.ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import image, recordio

        record = super().__getitem__(idx)
        header, img = recordio.unpack(record)
        arr = image.imdecode(img, flag=self._flag)
        if self._transform is not None:
            return self._transform(arr, header.label)
        return arr, header.label


class ImageFolderDataset(Dataset):
    """root/class/image.jpg layout (reference:
    gluon.data.vision.ImageFolderDataset)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        from .... import image

        img = image.imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
