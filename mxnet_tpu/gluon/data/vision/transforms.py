"""Vision transforms.

Reference parity: python/mxnet/gluon/data/vision/transforms.py — Compose,
Cast, ToTensor, Normalize, RandomResizedCrop, CenterCrop, Resize,
RandomFlipLeftRight/TopBottom, RandomBrightness/Contrast/Saturation/Hue/
ColorJitter/Lighting.
"""

from __future__ import annotations

import random as _pyrandom

import numpy as _np

from ...block import Block, HybridBlock
from ...nn import HybridSequential, Sequential
from ....ndarray.ndarray import NDArray, _from_jax


def _to_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


def _to_nd(x):
    import jax.numpy as jnp

    return _from_jax(jnp.asarray(x))


class Compose(Sequential):
    """Sequentially composes transforms (reference: transforms.Compose)."""

    def __init__(self, transforms):
        super().__init__()
        for i in transforms:
            self.add(i)


class Cast(HybridBlock):
    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return F.cast(x, dtype=self._dtype)


class ToTensor(HybridBlock):
    """HWC uint8 [0,255] → CHW float32 [0,1] (reference:
    transforms.ToTensor)."""

    def __init__(self):
        super().__init__()

    def hybrid_forward(self, F, x):
        out = F.cast(x, dtype="float32") / 255.0
        if out.ndim == 3:
            return F.transpose(out, axes=(2, 0, 1))
        return F.transpose(out, axes=(0, 3, 1, 2))


class Normalize(HybridBlock):
    """(x - mean) / std per channel of a CHW tensor."""

    def __init__(self, mean=0.0, std=1.0):
        super().__init__()
        self._mean = mean
        self._std = std

    def hybrid_forward(self, F, x):
        import jax.numpy as jnp

        mean = jnp.asarray(self._mean, dtype=jnp.float32)
        std = jnp.asarray(self._std, dtype=jnp.float32)
        nd = x.ndim
        if mean.ndim == 1:
            shape = [1] * nd
            shape[-3] = mean.shape[0]
            mean = mean.reshape(shape)
        if std.ndim == 1:
            shape = [1] * nd
            shape[-3] = std.shape[0]
            std = std.reshape(shape)
        return (x - mean) / std


class _HostTransform(Block):
    """Base for host-side (PIL/numpy) image transforms."""

    def forward(self, x):
        return _to_nd(self._apply(_to_np(x)))

    def _apply(self, arr):
        raise NotImplementedError


class Resize(_HostTransform):
    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def _apply(self, arr):
        from .... import image

        if isinstance(self._size, int):
            if self._keep:
                return image.resize_short_np(arr, self._size,
                                             self._interpolation)
            return image.imresize_np(arr, self._size, self._size,
                                     self._interpolation)
        w, h = self._size
        return image.imresize_np(arr, w, h, self._interpolation)


class CenterCrop(_HostTransform):
    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size,
                                                                   size)
        self._interpolation = interpolation

    def _apply(self, arr):
        from .... import image

        return image.center_crop_np(arr, self._size, self._interpolation)


class CropResize(_HostTransform):
    """Crop the fixed region (x, y, w, h) then optionally resize to
    ``size`` (reference: transforms.CropResize)."""

    def __init__(self, x, y, width, height, size=None, interpolation=1):
        super().__init__()
        self._box = (int(x), int(y), int(width), int(height))
        if size is not None and not isinstance(size, (tuple, list)):
            size = (size, size)
        self._size = tuple(size) if size is not None else None
        self._interpolation = interpolation

    def _apply(self, arr):
        from .... import image

        x, y, w, h = self._box
        return image.fixed_crop_np(arr, x, y, w, h, size=self._size,
                                   interp=self._interpolation)


class RandomResizedCrop(_HostTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = size if isinstance(size, (tuple, list)) else (size,
                                                                   size)
        self._scale = scale
        self._ratio = ratio
        self._interpolation = interpolation

    def _apply(self, arr):
        from .... import image

        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _pyrandom.uniform(*self._scale) * area
            log_ratio = (_np.log(self._ratio[0]), _np.log(self._ratio[1]))
            aspect = _np.exp(_pyrandom.uniform(*log_ratio))
            new_w = int(round(_np.sqrt(target_area * aspect)))
            new_h = int(round(_np.sqrt(target_area / aspect)))
            if new_w <= w and new_h <= h:
                x0 = _pyrandom.randint(0, w - new_w)
                y0 = _pyrandom.randint(0, h - new_h)
                return image.fixed_crop_np(arr, x0, y0, new_w, new_h,
                                           self._size, self._interpolation)
        return image.center_crop_np(arr, self._size, self._interpolation)


class RandomFlipLeftRight(_HostTransform):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def _apply(self, arr):
        if _pyrandom.random() < self._p:
            return arr[:, ::-1, :].copy()
        return arr


class RandomFlipTopBottom(_HostTransform):
    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def _apply(self, arr):
        if _pyrandom.random() < self._p:
            return arr[::-1, :, :].copy()
        return arr


class RandomBrightness(_HostTransform):
    def __init__(self, brightness):
        super().__init__()
        self._brightness = brightness

    def _apply(self, arr):
        alpha = 1.0 + _pyrandom.uniform(-self._brightness, self._brightness)
        return _np.clip(arr.astype(_np.float32) * alpha, 0, 255)


class RandomContrast(_HostTransform):
    def __init__(self, contrast):
        super().__init__()
        self._contrast = contrast

    def _apply(self, arr):
        alpha = 1.0 + _pyrandom.uniform(-self._contrast, self._contrast)
        arr = arr.astype(_np.float32)
        gray = (arr * _np.array([[[0.299, 0.587, 0.114]]])).sum(
            axis=2, keepdims=True)
        return _np.clip(arr * alpha + gray.mean() * (1 - alpha), 0, 255)


class RandomSaturation(_HostTransform):
    def __init__(self, saturation):
        super().__init__()
        self._saturation = saturation

    def _apply(self, arr):
        alpha = 1.0 + _pyrandom.uniform(-self._saturation,
                                        self._saturation)
        arr = arr.astype(_np.float32)
        gray = (arr * _np.array([[[0.299, 0.587, 0.114]]])).sum(
            axis=2, keepdims=True)
        return _np.clip(arr * alpha + gray * (1 - alpha), 0, 255)


class RandomHue(_HostTransform):
    def __init__(self, hue):
        super().__init__()
        self._hue = hue

    def _apply(self, arr):
        alpha = _pyrandom.uniform(-self._hue, self._hue)
        u = _np.cos(alpha * _np.pi)
        w = _np.sin(alpha * _np.pi)
        bt = _np.array([[1.0, 0.0, 0.0],
                        [0.0, u, -w],
                        [0.0, w, u]])
        tyiq = _np.array([[0.299, 0.587, 0.114],
                          [0.596, -0.274, -0.321],
                          [0.211, -0.523, 0.311]])
        ityiq = _np.array([[1.0, 0.956, 0.621],
                           [1.0, -0.272, -0.647],
                           [1.0, -1.107, 1.705]])
        t = ityiq @ bt @ tyiq
        return _np.clip(arr.astype(_np.float32) @ t.T, 0, 255)


class RandomColorJitter(_HostTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._transforms = []
        if brightness:
            self._transforms.append(RandomBrightness(brightness))
        if contrast:
            self._transforms.append(RandomContrast(contrast))
        if saturation:
            self._transforms.append(RandomSaturation(saturation))
        if hue:
            self._transforms.append(RandomHue(hue))

    def _apply(self, arr):
        order = list(self._transforms)
        _pyrandom.shuffle(order)
        for t in order:
            arr = t._apply(arr)
        return arr


class RandomLighting(_HostTransform):
    def __init__(self, alpha):
        super().__init__()
        self._alpha = alpha
        self._eigval = _np.array([55.46, 4.794, 1.148])
        self._eigvec = _np.array([[-0.5675, 0.7192, 0.4009],
                                  [-0.5808, -0.0045, -0.814],
                                  [-0.5836, -0.6948, 0.4203]])

    def _apply(self, arr):
        alpha = _np.random.normal(0, self._alpha, size=(3,))
        rgb = _np.dot(self._eigvec * alpha, self._eigval)
        return arr.astype(_np.float32) + rgb
