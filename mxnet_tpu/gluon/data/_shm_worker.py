"""Shared-memory DataLoader worker internals.

This module is deliberately numpy+stdlib only: workers never touch jax at
task time (collation is pure host work; device placement happens in the
parent), and NDArray samples are handled by duck-typing on ``asnumpy`` so
nothing here depends on the rest of the package.

Transport layout: the parent allocates ``nslots`` fixed-size
``multiprocessing.RawArray`` slots (anonymous shared mmap — no names, no
``resource_tracker`` bookkeeping, freed with the last handle) and hands one
free slot id out with every task.  A worker collates the batch *directly
into* numpy views of its slot — the collation copy is the transport copy —
and sends only ``(offset, shape, dtype)`` metadata through the result
queue.  Batches that don't fit the slot (or aren't flat numpy) fall back to
pickling through the queue, which is always correct, merely slower.
"""

from __future__ import annotations

import os as _os
import time as _time
import traceback

import numpy as _np

# slot offsets are aligned so every leaf view starts on a cache line
_ALIGN = 64


def _maybe_data_fault(batch_idx):
    """stdlib mirror of ``resilience.maybe_data_fault`` for spawn
    workers (this module must stay importable without the package):
    parses ``MXTPU_FAULT_INJECT`` directly for the two input-pipeline
    sites — ``worker_hang:K`` (the fetch of batch K sleeps
    ``MXTPU_DATA_HANG_SECS``, long past any receive timeout) and
    ``data_skew:K`` (fetches of the first K batches each sleep
    ``MXTPU_DATA_SKEW_SECS``)."""
    spec = _os.environ.get("MXTPU_FAULT_INJECT")
    if not spec:
        return
    for item in spec.split(","):
        site, _, arg = item.strip().partition(":")
        try:
            k = int(arg) if arg else 0
        except ValueError:
            continue
        if site == "worker_hang" and k == int(batch_idx):
            _time.sleep(float(_os.environ.get("MXTPU_DATA_HANG_SECS",
                                              10.0)))
        elif site == "data_skew" and int(batch_idx) < k:
            _time.sleep(float(_os.environ.get("MXTPU_DATA_SKEW_SECS",
                                              0.05)))


def _leaf_np(x):
    """One sample leaf -> numpy (duck-typed NDArray support)."""
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return _np.asarray(x)


def collate_column(column, out=None):
    """Collate one column of leaf samples into a single contiguous buffer.

    Single-copy: each sample is written once into the preallocated batch
    buffer.  Falls back to ``np.asarray`` (the legacy stacking path, with
    its promotion semantics) when samples disagree in shape or dtype.
    """
    arrs = [_leaf_np(a) for a in column]
    a0 = arrs[0]
    if any(a.shape != a0.shape or a.dtype != a0.dtype for a in arrs[1:]):
        return _np.asarray(arrs)
    if out is None:
        out = _np.empty((len(arrs),) + a0.shape, a0.dtype)
    for i, a in enumerate(arrs):
        out[i] = a
    return out


def collate_samples(samples):
    """Structure-preserving single-copy collation (the host half of
    ``default_batchify_fn``): tuple samples -> list of batch arrays."""
    first = samples[0]
    if isinstance(first, (list, tuple)):
        return [collate_samples(list(col)) for col in zip(*samples)]
    return collate_column(samples)


def _collate_into_slot(samples, buf):
    """Collate a batch of flat (non-nested) samples directly into `buf`.

    Returns ``(metas, is_list)`` with ``metas = [(offset, shape,
    dtype_str), ...]`` on success, or None when the batch needs the
    pickle fallback (nested samples, ragged shapes/dtypes, or the batch
    doesn't fit the slot).
    """
    first = samples[0]
    is_list = isinstance(first, (list, tuple))
    cols = list(zip(*samples)) if is_list else [samples]
    if any(isinstance(c[0], (list, tuple)) for c in cols):
        return None  # nested structure: rare, not worth a fast path
    off = 0
    views, metas = [], []
    for col in cols:
        a0 = _leaf_np(col[0])
        nbytes = int(_np.prod((len(col),) + a0.shape, dtype=_np.int64)) \
            * a0.dtype.itemsize
        off = (off + _ALIGN - 1) & ~(_ALIGN - 1)
        if off + nbytes > len(buf):
            return None
        shape = (len(col),) + a0.shape
        view = _np.frombuffer(buf, dtype=a0.dtype,
                              count=int(_np.prod(shape, dtype=_np.int64)),
                              offset=off).reshape(shape)
        views.append((view, a0, col))
        metas.append((off, shape, a0.dtype.str))
        off += nbytes
    for view, a0, col in views:
        view[0] = a0
        for i in range(1, len(col)):
            a = _leaf_np(col[i])
            if a.shape != a0.shape or a.dtype != a0.dtype:
                return None  # ragged: the generic path handles promotion
            view[i] = a
    return metas, is_list


def read_slot(buf, metas, is_list):
    """Parent-side: copy the collated arrays back out of a slot.

    The copy is what lets the slot be recycled immediately — on CPU
    backends ``jax.device_put`` may alias host memory zero-copy, so
    handing XLA a view of a ring slot that a worker will overwrite is a
    correctness hazard.  One memcpy still beats the 4+ copies of the
    pickle transport.
    """
    out = []
    for off, shape, dtype in metas:
        n = int(_np.prod(shape, dtype=_np.int64))
        out.append(_np.frombuffer(buf, dtype=_np.dtype(dtype), count=n,
                                  offset=off).reshape(shape).copy())
    return out if is_list else out[0]


def worker_loop(dataset, batchify_fn, slots, task_q, result_q):
    """Worker main: pull (batch_idx, slot_id, sample_indices) tasks until
    the None sentinel.  Out-of-order by construction — any idle worker
    pops the next task, so one slow batch delays only itself.

    ``batchify_fn is None`` selects the built-in single-copy collation
    (the common case, and the one that collates straight into the slot).
    """
    while True:
        task = task_q.get()
        if task is None:
            return
        batch_idx, slot_id, samples = task
        try:
            _maybe_data_fault(batch_idx)
            batch = [dataset[i] for i in samples]
            if batchify_fn is None:
                ok = _collate_into_slot(batch, slots[slot_id])
                if ok is not None:
                    metas, is_list = ok
                    result_q.put(("shm", batch_idx, slot_id, metas,
                                  is_list))
                    continue
                out = collate_samples(batch)
            else:
                out = batchify_fn(batch)
            result_q.put(("pickle", batch_idx, slot_id, out, None))
        except Exception as err:  # surfaced in the parent with context
            result_q.put(("error", batch_idx, slot_id,
                          (repr(err), traceback.format_exc(),
                           list(samples)), None))
