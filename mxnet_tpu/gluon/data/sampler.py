"""Samplers (reference: python/mxnet/gluon/data/sampler.py)."""

from __future__ import annotations

import numpy as _np


class Sampler:
    def __len__(self):
        raise NotImplementedError

    def __iter__(self):
        raise NotImplementedError


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length):
        self._length = length

    def __iter__(self):
        indices = _np.arange(self._length)
        _np.random.shuffle(indices)
        return iter(indices.tolist())

    def __len__(self):
        return self._length


class ResumableSampler(Sampler):
    """Seeded, shardable, checkpoint-resumable sample order.

    NEW, TPU-first (no reference analog): draws from a shared
    :class:`~mxnet_tpu.gluon.data.state.DataPipelineState` — each
    ``__iter__`` yields THIS rank's slice of the epoch's *remaining*
    sample space (``order[cursor:][rank::world]``; see state.py for the
    exactness model).  The epoch order is a pure function of
    ``(seed, epoch)``, never the global RNG, so a restored or reshaped
    gang reconstructs the identical order.  The cursor itself is
    advanced by the delivering iterator (DataLoader), not here:
    sampling runs ahead of delivery under prefetch, and the checkpoint
    must record what was delivered.
    """

    def __init__(self, state):
        self._state = state

    @property
    def state(self):
        return self._state

    def __iter__(self):
        return iter(self._state.shard().tolist())

    def __len__(self):
        return self._state.shard_len()


class FilterSampler(Sampler):
    def __init__(self, fn, dataset):
        self._fn = fn
        self._dataset = dataset
        self._indices = [i for i, sample in enumerate(dataset)
                         if fn(sample)]

    def __iter__(self):
        return iter(self._indices)

    def __len__(self):
        return len(self._indices)


class BatchSampler(Sampler):
    """Wraps a Sampler into batches with last_batch handling (reference:
    gluon.data.BatchSampler)."""

    def __init__(self, sampler, batch_size, last_batch="keep"):
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "discard":
                return
            elif self._last_batch == "rollover":
                self._prev = batch
            else:
                raise ValueError(
                    "last_batch must be one of 'keep', 'discard', or "
                    f"'rollover', but got {self._last_batch}")

    def __len__(self):
        if self._last_batch == "keep":
            return (len(self._sampler) + self._batch_size - 1) \
                // self._batch_size
        if self._last_batch == "discard":
            return len(self._sampler) // self._batch_size
        if self._last_batch == "rollover":
            return (len(self._prev) + len(self._sampler)) \
                // self._batch_size
        raise ValueError(
            "last_batch must be one of 'keep', 'discard', or 'rollover', "
            f"but got {self._last_batch}")
