"""Gluon: the imperative/hybrid frontend (reference: python/mxnet/gluon/)."""

from . import parameter
from .parameter import Parameter, Constant, ParameterDict
from .parameter import DeferredInitializationError
from . import block
from .block import Block, HybridBlock, SymbolBlock
from . import nn
from . import loss
from . import utils
from . import trainer
from .trainer import Trainer

# subpackages that land in later milestones are imported lazily so the core
# stays importable while they are being built
import importlib as _importlib

for _mod in ("rnn", "data", "model_zoo", "contrib"):
    try:
        globals()[_mod] = _importlib.import_module(f".{_mod}", __name__)
    except ModuleNotFoundError as _e:
        if _e.name != f"{__name__}.{_mod}":
            raise
del _importlib, _mod
