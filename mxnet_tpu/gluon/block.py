"""Gluon Block / HybridBlock.

Reference parity: python/mxnet/gluon/block.py — Block (dynamic graph,
name scopes, child registration, parameter collection, save/load) and
HybridBlock (``hybridize()``).

TPU-first redesign of the CachedOp (reference: src/imperative/cached_op.cc):
``hybridize()`` makes the whole block compile to ONE XLA program via
``jax.jit`` of a pure function ``(prng_key, params, *inputs) → (outputs,
aux_updates)``:

- parameters become explicit jit arguments (differentiable, never
  constant-folded) delivered to layers through a trace-time substitution
  scope;
- train-mode statefulness (BatchNorm moving stats) is functionalized: layers
  record new aux values into a collector during the trace; the compiled
  program returns them and the wrapper writes them back — replacing the
  reference's in-kernel aux mutation;
- randomness (Dropout) folds a per-call key argument (random.key_scope), so
  replays draw fresh masks without retracing;
- the autograd tape records ONE node holding the jit-vjp of the whole
  program: forward and backward each execute as a single compiled XLA
  program — the reference's CachedOp::Forward/Backward bulked segments,
  with XLA doing the memory planning the reference's nnvm passes did.
"""

from __future__ import annotations

import re
import threading
from collections import OrderedDict

from .. import autograd as _ag
from .. import name as _name
from ..base import MXNetError, np_dtype
from ..ndarray.ndarray import NDArray, _from_jax
from .parameter import (DeferredInitializationError, Parameter, ParameterDict)


class _TraceState(threading.local):
    def __init__(self):
        self.param_map = None    # id(Parameter) -> traced array
        self.aux_collector = None  # name -> raw new value
        self.force_eager = False


_TRACE = _TraceState()


class _BlockScope:
    """Name/parameter scoping for child blocks (reference: _BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = _name.current().get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        self._name_scope = _name.Prefix(self._block.prefix)
        self._name_scope.__enter__()
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        self._name_scope.__exit__(ptype, value, trace)
        self._name_scope = None
        _BlockScope._current.value = self._old_scope


class Block:
    """Base of all neural network layers and models (reference:
    gluon.Block)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            [f"  ({key}): " + repr(block).replace("\n", "\n  ")
             for key, block in self._children.items()])
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)):
                raise TypeError(
                    f"Changing attribute type for {self.name} from "
                    f"{type(existing)} to {type(value)} is not allowed.")
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed. " \
                "If you want to share parameters between blocks, please " \
                "set 'params' at Block construction instead."
            self._reg_params[name] = value
        super().__setattr__(name, value)

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """All Parameters of this block and children, optionally filtered by
        regex `select` (reference: Block.collect_params)."""
        self._check_container_with_block()
        ret = ParameterDict(self._params.prefix)
        if not select:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({name: value for name, value in self.params.items()
                        if pattern.match(name)})
        for cld in self._children.values():
            ret.update(cld.collect_params(select=select))
        return ret

    def _check_container_with_block(self):
        children = set(self._children.values())
        for k, v in self.__dict__.items():
            if isinstance(v, (list, tuple, dict)) and k != "_children":
                flat = v.values() if isinstance(v, dict) else v
                for item in flat:
                    if isinstance(item, Block) and item not in children:
                        import warnings

                        warnings.warn(
                            f'"{item}" is an unregistered container with '
                            "Blocks. Note that Blocks inside the list, tuple "
                            "or dict will not be registered automatically. "
                            "Make sure to register them using "
                            "register_child() or switching to "
                            "nn.Sequential/nn.HybridSequential instead.")

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + key: val for key, val in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    def save_parameters(self, filename, deduplicate=False):
        """Save parameters with structural names (reference:
        Block.save_parameters → .params file format)."""
        from ..ndarray import save as nd_save

        params = self._collect_params_with_prefix()
        if deduplicate:
            reverse_params = {}
            for k, v in params.items():
                if v not in reverse_params.values():
                    reverse_params[k] = v
            params = reverse_params
        arg_dict = {key: val._reduce() if hasattr(val, "_reduce")
                    else val.data() for key, val in params.items()}
        nd_save(filename, arg_dict)

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False,
                        dtype_source="current"):
        """Load from save_parameters format; also accepts full-name
        (save_params legacy / ParameterDict.save) files."""
        from ..ndarray import load as nd_load

        loaded = nd_load(filename)
        params = self._collect_params_with_prefix()
        if not loaded and not params:
            return
        if not any("." in i for i in loaded.keys()):
            # legacy full-prefix format
            del loaded
            self.collect_params().load(
                filename, ctx, allow_missing, ignore_extra, self.prefix,
                cast_dtype=cast_dtype, dtype_source=dtype_source)
            return
        if not allow_missing:
            for name in params.keys():
                assert name in loaded, \
                    f"Parameter '{name}' is missing in file '{filename}', " \
                    f"which contains parameters: {_brief_print_list(loaded.keys())}. " \
                    "Set allow_missing=True to ignore missing parameters."
        for name in loaded:
            if not ignore_extra and name not in params:
                raise ValueError(
                    f"Parameter '{name}' loaded from file '{filename}' is "
                    "not present in ParameterDict, which contains parameters "
                    f"{_brief_print_list(params.keys())}. Set "
                    "ignore_extra=True to ignore.")
            if name in params:
                params[name]._load_init(loaded[name], ctx,
                                        cast_dtype=cast_dtype,
                                        dtype_source=dtype_source)

    save_params = save_parameters
    load_params = load_parameters

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def apply(self, fn):
        """Apply fn recursively to self and children (reference:
        Block.apply)."""
        for cld in self._children.values():
            cld.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer as _init

        self.collect_params().initialize(
            init if init is not None else _init.Uniform(), ctx, verbose,
            force_reinit)

    def hybridize(self, active=True, **kwargs):
        for cld in self._children.values():
            cld.hybridize(active, **kwargs)

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary table (reference: Block.summary)."""
        from ..visualization import block_summary

        block_summary(self, *inputs)


def _brief_print_list(lst, limit=7):
    lst = list(lst)
    if len(lst) > limit:
        return _brief_print_list(lst[:limit // 2], limit) + ", ..., " + \
            _brief_print_list(lst[-limit // 2:], limit)
    return ", ".join([f"'{str(i)}'" for i in lst])


class HybridBlock(Block):
    """A Block compilable into one XLA program (reference: gluon.HybridBlock
    + src/imperative/cached_op.cc; see module docstring for the design)."""

    # activation sharding annotation (parallel/sharding.py): a
    # (spec_tuple, mesh) pair applied to this block's forward output via
    # with_sharding_constraint — class attr so pre-existing instances
    # and __setattr__-before-__init__ paths read None cheaply
    _act_spec = None

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = []
        self._jit_fns = {}
        self._param_order = None
        if not hasattr(self, "_cache_version"):
            self._cache_version = 0

    def __setattr__(self, name, value):
        super().__setattr__(name, value)
        if isinstance(value, (HybridBlock, Parameter)):
            self._clear_cached_op()

    def _clear_cached_op(self):
        self._jit_fns = {}
        self._param_order = None
        # Monotonic structure-version: every event that invalidates the
        # CachedOp (parameter set, register_child, hybridize, cast, LoRA
        # attach/detach) lands here, so external caches keyed on this
        # block (Trainer's captured train_step) invalidate on the same
        # events.  getattr: __setattr__ fires before __init__ finishes.
        self._cache_version = getattr(self, "_cache_version", 0) + 1

    def register_child(self, block, name=None):
        if not isinstance(block, HybridBlock):
            raise ValueError(
                f"Children of HybridBlock must also be HybridBlock, but "
                f"{str(block)} has type {str(type(block))}. If you are using "
                "Sequential, please try HybridSequential instead.")
        super().register_child(block, name)
        self._clear_cached_op()

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._flags = list(kwargs.items())
        self._clear_cached_op()
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def infer_shape(self, *args):
        """Complete deferred parameter shapes from input shapes.  Built-in
        layers override; composite blocks resolve child-by-child during the
        eager pass, so they don't need to."""
        raise ValueError(
            f"Deferred initialization failed because shape cannot be "
            f"inferred for block {self.name}. Override infer_shape, or "
            "construct the layer with explicit input dims.")

    def infer_type(self, *args):
        pass

    def optimize_for(self, x, backend="XLA", *extra, **kwargs):
        """Partition this block's traced graph for a subgraph backend
        and return a SymbolBlock running the partitioned graph with the
        current parameters bound (reference: HybridBlock.optimize_for,
        ≥1.6 — the MKLDNN/TensorRT offload entry).  ``x`` warms the
        trace exactly like the reference's sample input."""
        from .. import symbol as _sym

        if not self._active:
            self.hybridize()
        self(x, *extra)  # materialize deferred shapes / build the cache
        # trace with explicit, ordered input names so multi-input blocks
        # bind positionally in SymbolBlock (a hard-coded single 'data'
        # var mis-binds them)
        n_in = 1 + len(extra)
        in_names = ["data"] if n_in == 1 else \
            [f"data{i}" for i in range(n_in)]
        sym = _sym.trace_block(self, inputs=in_names)
        psym = sym.optimize_for(backend, **kwargs)
        sb = SymbolBlock(psym, [_sym.var(n) for n in in_names])
        params = self.collect_params()
        for name, p in sb.params.items():
            if name in params:
                p._load_init(params[name].data(), None, cast_dtype=True)
        return sb

    def export(self, path, epoch=0):
        """Serialize to symbol.json + params (reference: HybridBlock.export
        → the deploy format)."""
        from .. import symbol as _sym

        if not self._active:
            raise RuntimeError(
                "Please first call block.hybridize() and then run forward "
                "with this block at least once before calling export.")
        sym = _sym.trace_block(self)
        sym.save(f"{path}-symbol.json")
        from ..ndarray import save as nd_save

        # arg:/aux: keyed by the SAME global names the traced Variables
        # carry (Parameter.name), the reference's deploy convention —
        # SymbolBlock.imports matches sym.list_inputs() against these
        arg_dict = {}
        for name, param in self.collect_params().items():
            tag = "aux" if param.grad_req == "null" else "arg"
            arg_dict[f"{tag}:{name}"] = param.data()
        nd_save(f"{path}-{epoch:04d}.params", arg_dict)
        return sym

    # -- activation sharding ---------------------------------------------------

    def shard_activations(self, spec, mesh=None):
        """Pin this block's forward output to a PartitionSpec (Megatron
        activation annotation, e.g. ``('dp', None, 'tp')`` after a
        column-parallel projection).  ``mesh=None`` resolves the process
        default mesh at call time.  Takes effect inside every jit that
        traces this block — CachedOp forward and the captured train
        step — and is a no-op when no mesh (or a trivial one) is
        active, so annotated models still run unsharded."""
        self._act_spec = (tuple(spec), mesh)
        self._clear_cached_op()
        return self

    def _constrain_out(self, out):
        if self._act_spec is None:
            return out
        from ..parallel.mesh import default_mesh
        from ..parallel.sharding import constrain

        spec, mesh = self._act_spec
        if mesh is None:
            mesh = default_mesh()
        if mesh is None:
            return out

        def one(v):
            if isinstance(v, NDArray):
                v._set_data(constrain(v._data, mesh, spec))
                return v
            if hasattr(v, "ndim"):
                return constrain(v, mesh, spec)
            return v

        if isinstance(out, (tuple, list)):
            return type(out)(one(v) for v in out)
        return one(out)

    # -- forward dispatch ------------------------------------------------------

    def forward(self, x, *args):
        if isinstance(x, NDArray):
            # remember the seen input shapes: the export trace attaches
            # them to its Variables so hybrid_forward code reading
            # x.shape keeps working symbolically
            self._last_input_shapes = [
                tuple(a.shape) if hasattr(a, "shape") else None
                for a in (x,) + args]
            if self._active and not _TRACE.force_eager:
                return self._call_cached_op(x, *args)
            return self._eager_forward(x, *args)
        from ..symbol import Symbol as _Symbol

        if isinstance(x, _Symbol):
            # symbolic dual dispatch (reference: F=mx.sym in
            # hybrid_forward): parameters become named Variables so the
            # traced graph round-trips through symbol.json + .params
            from .. import symbol as _sym_mod

            params = {}
            for k, p in self._reg_params.items():
                v = p.var()
                if p.grad_req == "null":
                    v._set_attr(__aux__=True)
                    v.attrs["__aux__"] = True
                params[k] = v
            return self.hybrid_forward(_sym_mod, x, *args, **params)
        # raw array / tracer: pure path inside an enclosing trace
        params = {}
        for k, p in self._reg_params.items():
            pm = _TRACE.param_map
            if pm is not None and id(p) in pm:
                params[k] = pm[id(p)]
            else:
                params[k] = p.data()._data
        from .. import ndarray as F

        return self._constrain_out(
            self.hybrid_forward(F, x, *args, **params))

    def _eager_forward(self, x, *args):
        from .. import ndarray as F

        try:
            params = {k: p.data() for k, p in self._reg_params.items()}
        except DeferredInitializationError:
            self._deferred_infer_shape(x, *args)
            for p in self._reg_params.values():
                p._finish_deferred_init()
            params = {k: p.data() for k, p in self._reg_params.items()}
        return self._constrain_out(
            self.hybrid_forward(F, x, *args, **params))

    def _deferred_infer_shape(self, x, *args):
        self.infer_shape(x, *args)

    def _ensure_initialized(self, *args):
        try:
            for p in self.collect_params().values():
                if p._deferred_init:
                    raise DeferredInitializationError(p.name)
        except DeferredInitializationError:
            # one throwaway eager pass materializes every deferred shape
            # child-by-child (the reference runs the nnvm InferShape pass)
            prev = _TRACE.force_eager
            _TRACE.force_eager = True
            try:
                with _ag.pause():
                    self.forward(*args)
            finally:
                _TRACE.force_eager = prev

    def _get_jit_fn(self, training, args_tree, static_sig):
        cache_key = (training, args_tree, static_sig)
        fn = self._jit_fns.get(cache_key)
        if fn is not None:
            return fn
        import jax
        import jax.tree_util as jtu

        from .. import random as _random

        static_vals = dict(static_sig)

        def pure_step(key, param_vals, dyn_flat):
            flat = list(dyn_flat)
            for i, v in static_vals.items():
                flat.insert(i, v)
            call_args = jtu.tree_unflatten(args_tree, flat)
            pm = {pid: val for pid, val in
                  zip(self._param_order_ids, param_vals)}
            aux = {}
            with param_override_scope(pm, aux), \
                    _random.key_scope(key), \
                    (_ag.train_mode() if training
                     else _ag.predict_mode()):
                out = self.forward(*call_args)
            return out, aux

        # hybridize(remat=...) / MXNET_BACKWARD_DO_MIRROR: backward
        # recomputes activations (reference mirror pass; remat.py)
        from .. import remat as _remat

        pure_step = _remat.wrap(pure_step,
                                dict(self._flags).get("remat"))
        fn = jax.jit(pure_step)
        self._jit_fns[cache_key] = fn
        return fn

    def _call_cached_op(self, *args):
        """The CachedOp replay path: one compiled XLA program per
        (args-structure, shape-signature, train-mode).  Arguments may be
        arbitrary pytrees of NDArrays (e.g. the RNN `(x, [h, c])` call
        pattern); non-array leaves are compile-time constants."""
        import jax
        import jax.numpy as jnp
        import jax.tree_util as jtu

        from .. import random as _random

        self._ensure_initialized(*args)
        if self._param_order is None:
            allp = self.collect_params()
            self._param_order = list(allp.items())
            self._param_order_ids = [id(p) for _, p in self._param_order]

        flat_args, args_tree = jtu.tree_flatten(tuple(args))
        dyn_idx = [i for i, a in enumerate(flat_args)
                   if isinstance(a, NDArray) or hasattr(a, "shape")]
        dyn_set = set(dyn_idx)
        try:
            static_sig = tuple((i, a) for i, a in enumerate(flat_args)
                               if i not in dyn_set)
            hash(static_sig)
        except TypeError:
            static_sig = tuple((i, repr(a)) for i, a in enumerate(flat_args)
                               if i not in dyn_set)
        nd_pos_in_dyn = [j for j, i in enumerate(dyn_idx)
                         if isinstance(flat_args[i], NDArray)]
        nd_inputs = [flat_args[i] for i in dyn_idx
                     if isinstance(flat_args[i], NDArray)]
        dyn_raw = [flat_args[i]._data if isinstance(flat_args[i], NDArray)
                   else flat_args[i] for i in dyn_idx]

        param_nds = [p.data() for _, p in self._param_order]
        param_vals = [p._data for p in param_nds]
        training = _ag.is_training()
        jfn = self._get_jit_fn(training, args_tree, static_sig)
        key = _random.next_key()

        recording = _ag.is_recording() and (
            any(a._on_tape() for a in nd_inputs)
            or any(p._on_tape() for p in param_nds))

        if not recording:
            out, aux = jfn(key, param_vals, dyn_raw)
            self._write_aux(aux)
            out_leaves, out_tree = jtu.tree_flatten(out)
            return jtu.tree_unflatten(out_tree,
                                      [_from_jax(o) for o in out_leaves])

        out_aux, vjp_fn = jax.vjp(
            lambda pv, dr: jfn(key, pv, dr), param_vals, dyn_raw)
        out, aux = out_aux
        self._write_aux(aux)
        out_leaves, out_tree = jtu.tree_flatten(out)
        outs = [_from_jax(o) for o in out_leaves]
        aux_zero = jtu.tree_map(jnp.zeros_like, aux)
        n_out = len(outs)

        def tape_vjp(out_ct):
            cts = [out_ct] if n_out == 1 else list(out_ct)
            full_ct = (jtu.tree_unflatten(out_tree, cts), aux_zero)
            pv_ct, dyn_ct = vjp_fn(full_ct)
            return list(pv_ct) + [dyn_ct[j] for j in nd_pos_in_dyn]

        n_params = len(param_vals)

        def tape_pure(*raw):
            pv = list(raw[:n_params])
            dr = list(dyn_raw)
            for j, v in zip(nd_pos_in_dyn, raw[n_params:]):
                dr[j] = v
            out_p, _aux = jfn(key, pv, dr)
            leaves, _ = jtu.tree_flatten(out_p)
            return tuple(leaves) if len(leaves) > 1 else leaves[0]

        node = _ag.TapeNode(tape_vjp, param_nds + nd_inputs, outs,
                            name=f"CachedOp:{self.name}",
                            pure_fn=tape_pure)
        for o in outs:
            o._tape_node = node
        return jtu.tree_unflatten(out_tree, outs)

    def _write_aux(self, aux):
        if not aux:
            return
        with _ag.pause():
            byname = dict(self._param_order)
            for name, val in aux.items():
                p = byname.get(name)
                if p is not None:
                    p.data()._set_data(val)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


import contextlib


@contextlib.contextmanager
def param_override_scope(param_map, collected):
    """Run a block functionally: Parameters whose id() is in
    ``param_map`` read the mapped value instead of their stored data,
    and aux updates recorded via :func:`record_aux_update` land in the
    ``collected`` dict (keyed by param name).  The ONE home of the
    save/set/restore protocol — the whole-block jit path, the sharded
    trainer, and the pipeline trainer all enter through here.
    """
    prev_map, prev_aux = _TRACE.param_map, _TRACE.aux_collector
    _TRACE.param_map = param_map
    _TRACE.aux_collector = collected
    try:
        yield
    finally:
        _TRACE.param_map, _TRACE.aux_collector = prev_map, prev_aux


def record_aux_update(param_name, raw_value):
    """Layers call this to update an aux (non-differentiable) parameter from
    inside hybrid_forward — functionalized under a trace, immediate eagerly.

    Replaces the reference's in-kernel aux-state mutation
    (e.g. BatchNorm moving_mean, src/operator/nn/batch_norm.cc).
    """
    col = _TRACE.aux_collector
    if col is not None:
        col[param_name] = raw_value
        return True
    return False


class SymbolBlock(HybridBlock):
    """Run a loaded symbolic graph as a block (reference:
    gluon.SymbolBlock.imports for deploy-format models)."""

    @staticmethod
    def imports(symbol_file, input_names, param_file=None, ctx=None):
        from .. import symbol as _sym

        sym = _sym.load(symbol_file)
        if isinstance(input_names, str):
            input_names = [input_names]
        inputs = [_sym.var(i) for i in input_names]
        ret = SymbolBlock(sym, inputs)
        if param_file is not None:
            ret.collect_params().load(param_file, ctx=ctx, cast_dtype=True,
                                      dtype_source="saved",
                                      allow_missing=False, ignore_extra=True)
        return ret

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        from .. import symbol as _sym

        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        if isinstance(inputs, _sym.Symbol):
            inputs = [inputs]
        self._outputs_sym = outputs
        self._input_names = [i.name for i in inputs]
        input_set = set(self._input_names)
        # every non-input free variable becomes a parameter of this block,
        # under its EXACT traced name (no symbolblock prefix — the deploy
        # .params file is keyed by the original global names)
        from .parameter import Parameter as _Param

        aux = set(outputs.list_auxiliary_states())
        for name in outputs.list_inputs():
            if name not in input_set and name not in self.params._params:
                self.params._params[name] = _Param(
                    name, shape=None, dtype=None,
                    allow_deferred_init=True,
                    grad_req="null" if name in aux else "write")

    def forward(self, *args):
        from .. import symbol as _sym

        feed = dict(zip(self._input_names, args))
        for name, p in self.params.items():
            feed[name] = p.data()
        return self._outputs_sym.eval(**feed)

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError
