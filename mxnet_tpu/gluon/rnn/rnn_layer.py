"""Fused recurrent layers.

Reference parity: python/mxnet/gluon/rnn/rnn_layer.py — RNN, LSTM, GRU over
the fused RNN op (src/operator/rnn.cc).  Parameters are kept per
layer/direction ({l,r}{i}_{i2h,h2h}_{weight,bias}, matching the reference's
names for checkpoint compatibility) and packed into the op's single vector
at forward time — XLA fuses the concat away.
"""

from __future__ import annotations

import numpy as _np

from ... import autograd as _ag
from ...base import MXNetError
from ...ndarray.ndarray import NDArray, _from_jax
from ..block import HybridBlock
from ..parameter import Parameter


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, projection_size=None,
                 **kwargs):
        self._mode = mode  # before super(): _alias() runs in Block.__init__
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be one of ['TNC' or 'NTC']"
        if projection_size is not None and mode != "lstm":
            raise MXNetError("projection_size is LSTM-only (LSTMP)")
        self._hidden_size = hidden_size
        self._projection_size = projection_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4,
                       "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        rec = projection_size if projection_size else nh
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param(f"{j}{i}_i2h_weight", (ng * nh, ni),
                                     i2h_weight_initializer)
                self._register_param(f"{j}{i}_h2h_weight", (ng * nh, rec),
                                     h2h_weight_initializer)
                if projection_size:
                    # LSTMP recurrent projection (reference name: h2r)
                    self._register_param(f"{j}{i}_h2r_weight", (rec, nh),
                                         h2h_weight_initializer)
                self._register_param(f"{j}{i}_i2h_bias", (ng * nh,),
                                     i2h_bias_initializer)
                self._register_param(f"{j}{i}_h2h_bias", (ng * nh,),
                                     h2h_bias_initializer)
            ni = rec * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        setattr(self, name, p)

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = "{0} -> {1}".format(
            shape[1] if shape[1] else None, shape[0] // self._gates)
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def _alias(self):
        return self._mode

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def infer_shape(self, x, *args):
        ni = x.shape[2] if self._layout == "TNC" else x.shape[2]
        ng, nh = self._gates, self._hidden_size
        rec = self._projection_size or nh
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                getattr(self, f"{j}{i}_i2h_weight").shape = (ng * nh, ni)
            ni = rec * self._dir

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent states (reference: _RNNLayer.begin_state)."""
        from ... import ndarray as nd

        if func is None:
            func = nd.zeros
        states = []
        for i, info in enumerate(self.state_info(batch_size)):
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            states.append(func(name=f"{self.prefix}h0_{i}", **info))
        return states

    def _pack_params(self, F, kwargs):
        parts = []
        conns_w = ["i2h", "h2h"] + (
            ["h2r"] if self._projection_size else [])
        for t, conns in (("weight", conns_w), ("bias", ["i2h", "h2h"])):
            for i in range(self._num_layers):
                for j in ["l", "r"][:self._dir]:
                    for conn in conns:
                        name = f"{j}{i}_{conn}_{t}"
                        parts.append(F.reshape(kwargs[name], (-1,)))
        return F.concat(*parts, dim=0) if len(parts) > 1 else parts[0]

    def hybrid_forward(self, F, inputs, states=None, **kwargs):
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, 0, 1)
        batch_size = inputs.shape[1]
        skip_states = states is None
        if skip_states:
            import jax.numpy as jnp

            infos = self.state_info(batch_size)
            mk = lambda info: jnp.zeros(info["shape"], dtype=inputs.dtype
                                        if hasattr(inputs, "dtype")
                                        else "float32")
            states = [mk(info) for info in infos]
        if isinstance(states, (NDArray,)) or (
                hasattr(states, "shape") and not isinstance(states, list)):
            states = [states]
        params = self._pack_params(F, kwargs)
        state_cell = states[1] if self._mode == "lstm" else None
        out = F.RNN(inputs, params, states[0], state_cell,
                    state_size=self._hidden_size,
                    num_layers=self._num_layers, mode=self._mode,
                    bidirectional=self._dir == 2, p=self._dropout,
                    projection_size=self._projection_size,
                    state_outputs=True)
        if self._mode == "lstm":
            outputs, h, c = out
            new_states = [h, c]
        else:
            outputs, h = out
            new_states = [h]
        if self._layout == "NTC":
            outputs = F.swapaxes(outputs, 0, 1)
        if skip_states:
            return outputs
        return outputs, new_states


class RNN(_RNNLayer):
    """Vanilla RNN with relu/tanh (reference: gluon.rnn.RNN)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """LSTM (reference: gluon.rnn.LSTM; fused kernel rnn.cc)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 projection_size=None, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "lstm", projection_size, **kwargs)

    def state_info(self, batch_size=0):
        rec = self._projection_size or self._hidden_size
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           rec), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """GRU (reference: gluon.rnn.GRU; cuDNN gate order r z n)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         i2h_weight_initializer, h2h_weight_initializer,
                         i2h_bias_initializer, h2h_bias_initializer,
                         "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
