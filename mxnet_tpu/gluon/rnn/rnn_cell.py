"""Recurrent cells.

Reference parity: python/mxnet/gluon/rnn/rnn_cell.py — RecurrentCell,
RNNCell, LSTMCell, GRUCell, SequentialRNNCell, DropoutCell, ModifierCell,
ZoneoutCell, ResidualCell, BidirectionalCell, and ``unroll``.

Cells unroll as Python loops over mx.nd ops; under ``hybridize()`` the whole
unrolled graph compiles to one XLA program (the length-bucketed analog of
the reference's BucketingModule; for long sequences prefer the fused
gluon.rnn layers, which scan).
"""

from __future__ import annotations

from ...base import MXNetError
from ..block import Block, HybridBlock


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _get_begin_state(cell, F, begin_state, inputs, batch_size):
    if begin_state is None:
        begin_state = cell.begin_state(batch_size=batch_size)
    return begin_state


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    from ... import ndarray as nd
    from ...ndarray.ndarray import NDArray

    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, (NDArray,)) or hasattr(inputs, "shape"):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            if length is None:
                length = inputs.shape[axis]
            inputs = list(nd.split(inputs, axis=axis,
                                   num_outputs=inputs.shape[axis],
                                   squeeze_axis=1))
    else:
        assert length is None or len(inputs) == length
        batch_size = inputs[0].shape[batch_axis - (1 if batch_axis > axis
                                                   else 0)] \
            if False else inputs[0].shape[0]
        if merge is True:
            inputs = nd.stack(*inputs, axis=axis)
    if isinstance(inputs, list):
        length = len(inputs)
    return inputs, axis, batch_size, length


def _mask_sequence_variable_length(F, data, length, valid_length, time_axis,
                                   merged):
    from ... import ndarray as nd

    assert valid_length is not None
    if not isinstance(data, list):
        ret = nd.SequenceMask(data, sequence_length=valid_length,
                              use_sequence_length=True, axis=time_axis)
    else:
        ret = [nd.SequenceMask(ele, sequence_length=valid_length,
                               use_sequence_length=True, axis=time_axis)
               for ele in data]
    return ret


class RecurrentCell(Block):
    """Abstract cell (reference: rnn.RecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError()

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as nd

        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called " \
            "directly. Call the modifier cell instead."
        if func is None:
            func = nd.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            if info is not None:
                info.update(kwargs)
            else:
                info = kwargs
            state = func(name=f"{self._prefix}begin_state_"
                              f"{self._init_counter}", **info)
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell for `length` steps (reference:
        RecurrentCell.unroll)."""
        from ... import ndarray as nd

        self.reset()
        F = nd
        inputs, axis, batch_size, length = _format_sequence(
            length, inputs, layout, False)
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        states = begin_state
        outputs = []
        all_states = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
            if valid_length is not None:
                all_states.append(states)
        if valid_length is not None:
            states = [nd.SequenceLast(nd.stack(*ele_list, axis=0),
                                      sequence_length=valid_length,
                                      use_sequence_length=True, axis=0)
                      for ele_list in zip(*all_states)]
            outputs = _mask_sequence_variable_length(
                F, outputs, length, valid_length, axis, True)
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        return outputs, states

    def _get_activation(self, F, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def forward(self, inputs, states):
        self._counter += 1
        return super().forward(inputs, states)


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """Cell with hybrid_forward (reference: rnn.HybridRecurrentCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def forward(self, inputs, states):
        self._counter += 1
        return HybridBlock.forward(self, inputs, states)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell (reference: rnn.RNNCell)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prefix = f"t{self._counter}_"
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        i2h_plus_h2h = i2h + h2h
        output = self._get_activation(F, i2h_plus_h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (reference: rnn.LSTMCell; gates i f g o)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None,
                 activation="tanh", recurrent_activation="sigmoid"):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)
        self._activation = activation
        self._recurrent_activation = recurrent_activation

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (4 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=4 * self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = F.SliceChannel(gates, num_outputs=4, axis=-1)
        in_gate = self._get_activation(F, slice_gates[0],
                                       self._recurrent_activation)
        forget_gate = self._get_activation(F, slice_gates[1],
                                           self._recurrent_activation)
        in_transform = self._get_activation(F, slice_gates[2],
                                            self._activation)
        out_gate = self._get_activation(F, slice_gates[3],
                                        self._recurrent_activation)
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(F, next_c,
                                                 self._activation)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (reference: rnn.GRUCell; gates r z n)."""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x, *args):
        self.i2h_weight.shape = (3 * self._hidden_size, x.shape[-1])

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=3 * self._hidden_size)
        h2h = F.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                               num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h = F.SliceChannel(i2h, num_outputs=3, axis=-1)
        h2h_r, h2h_z, h2h = F.SliceChannel(h2h, num_outputs=3, axis=-1)
        reset_gate = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h + reset_gate * h2h, act_type="tanh")
        next_h = (1. - update_gate) * next_h_tmp \
            + update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stacks cells (reference: rnn.SequentialRNNCell)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        assert all(not isinstance(cell, BidirectionalCell)
                   for cell in self._children.values())
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args, **kwargs):
        raise NotImplementedError


class DropoutCell(HybridRecurrentCell):
    """Dropout on cell input (reference: rnn.DropoutCell)."""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        assert isinstance(rate, (int, float)), "rate must be a number"
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F, inputs, states):
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells that modify another cell (reference:
    rnn.ModifierCell)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified. One cell cannot be modified " \
            "twice" % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def hybrid_forward(self, F, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (reference: rnn.ZoneoutCell)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. " \
            "Please add ZoneoutCell to the cells underneath instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        mask = lambda p, like: F.Dropout(F.ones_like(like), p=p)
        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F.zeros_like(next_output)
        output = (F.where(mask(p_outputs, next_output), next_output,
                          prev_output)
                  if p_outputs != 0.0 else next_output)
        new_states = ([F.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0.0 else next_states)
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds input to output (reference: rnn.ResidualCell)."""

    def __init__(self, base_cell):
        super().__init__(base_cell)

    def _alias(self):
        return "residual"

    def hybrid_forward(self, F, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    """Runs two cells over opposite directions (reference:
    rnn.BidirectionalCell)."""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as nd

        self.reset()
        F = nd
        inputs, axis, batch_size, length = _format_sequence(
            length, inputs, layout, False)
        reversed_inputs = list(reversed(inputs))
        begin_state = _get_begin_state(self, F, begin_state, inputs,
                                       batch_size)
        states = begin_state
        l_cell, r_cell = self._children.values()
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs,
            begin_state=states[:len(l_cell.state_info())],
            layout=layout, merge_outputs=False,
            valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=reversed_inputs,
            begin_state=states[len(l_cell.state_info()):],
            layout=layout, merge_outputs=False,
            valid_length=valid_length)
        if valid_length is not None:
            r_outputs = list(reversed(
                _mask_sequence_variable_length(
                    F, list(reversed(r_outputs)), length, valid_length,
                    axis, True)))
        else:
            r_outputs = list(reversed(r_outputs))
        outputs = [nd.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, r_outputs)]
        if merge_outputs:
            outputs = nd.stack(*outputs, axis=axis)
        states = l_states + r_states
        return outputs, states
