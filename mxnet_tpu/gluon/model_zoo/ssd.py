"""SSD: Single Shot MultiBox Detector.

Reference parity: example/ssd/ (symbol/symbol_builder.py over the MultiBox
ops) — the BASELINE 'SSD/Mask-RCNN dynamic-shape' config.  Model: VGG-ish /
resnet features + multi-scale heads; anchors/targets/decode use the
static-shape detection ops (ops/contrib_det.py), so the whole
forward+loss compiles under jit — the reference's dynamic-shape risk item
(SURVEY §7) resolved with the padded-output convention.
"""

from __future__ import annotations

import numpy as _np

from ...ndarray.ndarray import NDArray, _from_jax
from ..block import HybridBlock
from .. import nn


class SSDAnchorGenerator(HybridBlock):
    """Per-feature-map anchors (reference: MultiBoxPrior usage in
    symbol_builder)."""

    def __init__(self, sizes, ratios, **kwargs):
        super().__init__(**kwargs)
        self._sizes = tuple(sizes)
        self._ratios = tuple(ratios)

    @property
    def num_anchors(self):
        return len(self._sizes) + len(self._ratios) - 1

    def hybrid_forward(self, F, x):
        return F.MultiBoxPrior(x, sizes=self._sizes, ratios=self._ratios)


class SSD(HybridBlock):
    """Compact SSD with a configurable backbone.

    Returns (cls_preds (B,C+1,N), loc_preds (B,N*4), anchors (1,N,4)).
    """

    def __init__(self, num_classes=20, base_channels=(32, 64, 128),
                 scale_sizes=((0.2,), (0.4,), (0.7,)),
                 scale_ratios=((1, 2, 0.5),) * 3, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        with self.name_scope():
            self.stages = nn.HybridSequential(prefix="backbone_")
            with self.stages.name_scope():
                for c in base_channels:
                    blk = nn.HybridSequential(prefix=f"stage{c}_")
                    with blk.name_scope():
                        blk.add(nn.Conv2D(c, 3, padding=1,
                                          use_bias=False),
                                nn.BatchNorm(),
                                nn.Activation("relu"),
                                nn.MaxPool2D(2))
                    self.stages.add(blk)
            self.anchor_gens = []
            self.cls_heads = nn.HybridSequential(prefix="cls_")
            self.loc_heads = nn.HybridSequential(prefix="loc_")
            for i, (sizes, ratios) in enumerate(zip(scale_sizes,
                                                    scale_ratios)):
                gen = SSDAnchorGenerator(sizes, ratios,
                                         prefix=f"anchor{i}_")
                self.anchor_gens.append(gen)
                setattr(self, f"anchor_gen{i}", gen)
                na = gen.num_anchors
                with self.cls_heads.name_scope():
                    self.cls_heads.add(nn.Conv2D(
                        na * (num_classes + 1), 3, padding=1))
                with self.loc_heads.name_scope():
                    self.loc_heads.add(nn.Conv2D(na * 4, 3, padding=1))

    def hybrid_forward(self, F, x):
        cls_preds, loc_preds, anchors = [], [], []
        stages = list(self.stages._children.values())
        cls_heads = list(self.cls_heads._children.values())
        loc_heads = list(self.loc_heads._children.values())
        for stage, gen, cls_head, loc_head in zip(
                stages, self.anchor_gens, cls_heads, loc_heads):
            x = stage(x)
            anchors.append(gen(x))
            c = cls_head(x)          # (B, A*(C+1), H, W)
            l = loc_head(x)          # (B, A*4, H, W)
            # shape-free reshape (0 = keep batch) so the graph traces
            # symbolically for export
            cls_preds.append(
                F.reshape(F.transpose(c, axes=(0, 2, 3, 1)),
                          shape=(0, -1, self.num_classes + 1)))
            loc_preds.append(
                F.reshape(F.transpose(l, axes=(0, 2, 3, 1)),
                          shape=(0, -1)))
        cls_all = F.concat(*cls_preds, dim=1)     # (B, N, C+1)
        loc_all = F.concat(*loc_preds, dim=1)     # (B, N*4)
        anc_all = F.concat(*anchors, dim=1)       # (1, N, 4)
        return (F.transpose(cls_all, axes=(0, 2, 1)), loc_all, anc_all)


def _ssd_loss_pure(cls_p, loc_p, anc, lab, ratio=3):
    """cls_p (B,C+1,N), loc_p (B,N*4), anc (1,N,4), lab (B,M,5)."""
    import jax
    import jax.numpy as jnp

    from ...ops.contrib_det import multibox_target

    loc_t, loc_m, cls_t = multibox_target(
        anc, lab, jax.nn.softmax(cls_p, axis=1),
        negative_mining_ratio=ratio)
    # classification: CE over anchors with cls_t >= 0 (mined-out negatives
    # carry ignore_label and drop out)
    logp = jax.nn.log_softmax(cls_p, axis=1)         # (B, C+1, N)
    tgt = jnp.maximum(cls_t, 0).astype(jnp.int32)    # (B, N)
    nll = -jnp.take_along_axis(logp, tgt[:, None, :], axis=1)[:, 0]
    valid = (cls_t >= 0)
    denom = jnp.maximum(jnp.sum(valid), 1)
    cls_loss = jnp.sum(jnp.where(valid, nll, 0.0)) / denom
    # localization: smooth-L1 on matched anchors
    diff = (loc_p - loc_t) * loc_m
    absd = jnp.abs(diff)
    sl1 = jnp.where(absd < 1.0, 0.5 * diff * diff, absd - 0.5)
    pos = jnp.maximum(jnp.sum(loc_m) / 4.0, 1.0)
    loc_loss = jnp.sum(sl1) / pos
    return cls_loss + loc_loss


class SSDTrainLoss(HybridBlock):
    """MultiBoxTarget + cls CE (ignoring mined-out negatives) + smooth-L1
    loc loss (reference: example/ssd training_targets + MultiBoxTarget).

    Routed through the invoke layer so the whole loss records ONE tape
    node eagerly and traces pure under jit."""

    def __init__(self, negative_mining_ratio=3, **kwargs):
        super().__init__(**kwargs)
        self._ratio = negative_mining_ratio

    def hybrid_forward(self, F, outputs, label):
        import functools

        from ...ndarray.register import invoke_simple

        cls_preds, loc_preds, anchors = outputs
        fn = functools.partial(_ssd_loss_pure, ratio=self._ratio)
        fn.__name__ = "ssd_loss"
        return invoke_simple(fn, (cls_preds, loc_preds, anchors, label))


def ssd_detect(net, x, nms_threshold=0.45, score_threshold=0.01,
               nms_topk=400):
    """Inference: forward + MultiBoxDetection decode (reference:
    example/ssd/demo.py path).  Returns (B, N, 6) [id, score, box]."""
    import jax

    from ... import ndarray as nd

    cls_preds, loc_preds, anchors = net(x)
    probs = nd.softmax(nd.transpose(cls_preds, axes=(0, 2, 1)),
                       axis=-1)  # (B, N, C+1)
    probs = nd.transpose(probs, axes=(0, 2, 1))  # (B, C+1, N)
    return nd.MultiBoxDetection(probs, loc_preds, anchors,
                                nms_threshold=nms_threshold,
                                threshold=score_threshold,
                                nms_topk=nms_topk)
