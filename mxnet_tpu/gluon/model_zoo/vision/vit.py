"""Vision Transformer (Dosovitskiy et al. 2021).

Beyond reference scope (the 2018-era reference zoo stops at CNNs) but
the natural TPU flagship for image classification: one big patchify
matmul + the same scanned pre-LN encoder trunk the BERT/GPT families
compile through (`ops/transformer.scan_transformer_encoder`), so the
whole model is two MXU-dense stages with flash attention available via
``attention_impl="flash"``.

Weight layout notes:
- patch embedding is a Conv2D(units, k=patch, s=patch) — XLA lowers it
  to one matmul over unfolded patches;
- cls token + learned position embedding, standard pre-LN trunk,
  classification head on the cls position.
"""

from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from ..bert import ScanTransformerEncoder, TransformerEncoder

__all__ = ["VisionTransformer", "vit_tiny", "vit_small", "vit_base",
           "vit_large"]


class VisionTransformer(HybridBlock):
    def __init__(self, image_size=224, patch_size=16, units=768,
                 num_layers=12, num_heads=12, hidden_size=None,
                 classes=1000, dropout=0.0, attention_impl="dense",
                 scan_layers=True, remat=False, **kwargs):
        super().__init__(**kwargs)
        assert image_size % patch_size == 0, \
            f"image_size {image_size} must be divisible by patch_size " \
            f"{patch_size}"
        n_patches = (image_size // patch_size) ** 2
        self._units = units
        self._dropout = dropout
        with self.name_scope():
            self.patch_embed = nn.Conv2D(
                units, kernel_size=patch_size, strides=patch_size,
                prefix="patch_embed_")
            self.cls_token = self.params.get(
                "cls_token", shape=(1, 1, units), init="zeros")
            self.pos_embed = self.params.get(
                "pos_embed", shape=(1, n_patches + 1, units),
                init="normal")
            if remat and not scan_layers:
                raise ValueError(
                    "VisionTransformer: remat=True requires "
                    "scan_layers=True (per-layer remat lives in the "
                    "scanned trunk)")
            enc = ScanTransformerEncoder if scan_layers \
                else TransformerEncoder
            enc_kwargs = {"remat": remat} if scan_layers else {}
            self.encoder = enc(
                num_layers=num_layers, units=units, num_heads=num_heads,
                hidden_size=hidden_size, dropout=dropout,
                attention_impl=attention_impl, prefix="encoder_",
                **enc_kwargs)
            self.head = nn.Dense(classes, in_units=units,
                                 prefix="head_")
            if dropout:
                self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, cls_token, pos_embed):
        # shape-free forms throughout (reshape 0/-1, broadcast_like) so
        # the same code traces symbolically for export/deploy
        p = self.patch_embed(x)                     # (B, U, H/ps, W/ps)
        p = F.reshape(p, shape=(0, self._units, -1))
        p = F.transpose(p, axes=(0, 2, 1))          # (B, N, U)
        cls = F.broadcast_like(
            cls_token, F.slice_axis(p, axis=1, begin=0, end=1))
        h = F.broadcast_add(F.concat(cls, p, dim=1), pos_embed)
        if self._dropout:
            h = self.drop(h)
        h = self.encoder(h)
        return self.head(F.reshape(
            F.slice_axis(h, axis=1, begin=0, end=1),
            shape=(-1, self._units)))


def vit_tiny(image_size=32, patch_size=4, classes=10, **kwargs):
    """CI-scale ViT (32x32/p4 defaults for tests and examples)."""
    return VisionTransformer(image_size, patch_size, units=64,
                             num_layers=4, num_heads=4, classes=classes,
                             **kwargs)


def vit_small(image_size=224, patch_size=16, classes=1000, **kwargs):
    return VisionTransformer(image_size, patch_size, units=384,
                             num_layers=12, num_heads=6, classes=classes,
                             **kwargs)


def vit_base(image_size=224, patch_size=16, classes=1000, **kwargs):
    return VisionTransformer(image_size, patch_size, units=768,
                             num_layers=12, num_heads=12,
                             classes=classes, **kwargs)


def vit_large(image_size=224, patch_size=16, classes=1000, **kwargs):
    return VisionTransformer(image_size, patch_size, units=1024,
                             num_layers=24, num_heads=16,
                             classes=classes, **kwargs)
