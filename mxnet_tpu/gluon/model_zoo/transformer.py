"""Encoder-decoder Transformer (NMT family).

Reference context: the reference repo ships transformer kernels
(src/operator/contrib/transformer.cu) and the seq2seq models live in
external packages (sockeye/gluon-nlp, the BASELINE "NMT at long seq"
config — SURVEY §5.7).  Provided natively, TPU-first: packed-QKV
self-attention (causal in the decoder), cross-attention over encoder
memory, pre-LN everywhere, label-smoothed loss, and greedy decode via
a python loop (host-driven; each step is a jitted forward under
hybridize).

Sequence parallelism: attention impl is selectable ('dense', 'flash',
'ring') exactly as in the BERT family.
"""

from __future__ import annotations

from ..block import HybridBlock
from .. import nn
from .bert import TransformerEncoder


class TransformerDecoderLayer(HybridBlock):
    """Pre-LN decoder layer: causal self-attn → cross-attn → FFN."""

    def __init__(self, units, num_heads, hidden_size=None, dropout=0.1,
                 attention_impl="dense", activation="relu", **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        hidden_size = hidden_size or 4 * units
        self._units = units
        self._num_heads = num_heads
        self._dropout = dropout
        self._attention_impl = attention_impl
        self._activation = activation
        with self.name_scope():
            self.self_qkv_weight = self.params.get(
                "self_qkv_weight", shape=(3 * units, units))
            self.self_qkv_bias = self.params.get(
                "self_qkv_bias", shape=(3 * units,), init="zeros")
            self.self_proj_weight = self.params.get(
                "self_proj_weight", shape=(units, units))
            self.self_proj_bias = self.params.get(
                "self_proj_bias", shape=(units,), init="zeros")
            self.cross_qkv_weight = self.params.get(
                "cross_qkv_weight", shape=(3 * units, units))
            self.cross_qkv_bias = self.params.get(
                "cross_qkv_bias", shape=(3 * units,), init="zeros")
            self.cross_proj_weight = self.params.get(
                "cross_proj_weight", shape=(units, units))
            self.cross_proj_bias = self.params.get(
                "cross_proj_bias", shape=(units,), init="zeros")
            self.ffn1_weight = self.params.get(
                "ffn1_weight", shape=(hidden_size, units))
            self.ffn1_bias = self.params.get(
                "ffn1_bias", shape=(hidden_size,), init="zeros")
            self.ffn2_weight = self.params.get(
                "ffn2_weight", shape=(units, hidden_size))
            self.ffn2_bias = self.params.get(
                "ffn2_bias", shape=(units,), init="zeros")
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.ln2 = nn.LayerNorm(in_channels=units)
            self.ln3 = nn.LayerNorm(in_channels=units)
            if dropout:
                self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, memory, self_qkv_weight,
                       self_qkv_bias, self_proj_weight, self_proj_bias,
                       cross_qkv_weight, cross_qkv_bias,
                       cross_proj_weight, cross_proj_bias, ffn1_weight,
                       ffn1_bias, ffn2_weight, ffn2_bias):
        h = self.ln1(x)
        attn = F.multi_head_attention(
            h, h, h, qkv_weight=self_qkv_weight,
            qkv_bias=self_qkv_bias, proj_weight=self_proj_weight,
            proj_bias=self_proj_bias, num_heads=self._num_heads,
            causal=True, impl=self._attention_impl)
        if self._dropout:
            attn = self.drop(attn)
        x = x + attn
        h = self.ln2(x)
        cross = F.multi_head_attention(
            h, memory, memory, qkv_weight=cross_qkv_weight,
            qkv_bias=cross_qkv_bias, proj_weight=cross_proj_weight,
            proj_bias=cross_proj_bias, num_heads=self._num_heads,
            impl="dense")
        if self._dropout:
            cross = self.drop(cross)
        x = x + cross
        h = self.ln3(x)
        h = F.FullyConnected(h, ffn1_weight, ffn1_bias,
                             num_hidden=ffn1_weight.shape[0],
                             flatten=False)
        h = F.Activation(h, act_type=self._activation)
        h = F.FullyConnected(h, ffn2_weight, ffn2_bias,
                             num_hidden=ffn2_weight.shape[0],
                             flatten=False)
        if self._dropout:
            h = self.drop(h)
        return x + h


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers, units, num_heads, hidden_size=None,
                 dropout=0.1, attention_impl="dense", **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.layers = []
            for i in range(num_layers):
                layer = TransformerDecoderLayer(
                    units, num_heads, hidden_size, dropout,
                    attention_impl, prefix=f"layer{i}_")
                self.register_child(layer)
                self.layers.append(layer)
            self.ln_f = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x, memory):
        for layer in self.layers:
            x = layer(x, memory)
        return self.ln_f(x)


class Transformer(HybridBlock):
    """Full encoder-decoder Transformer for seq2seq (NMT)."""

    def __init__(self, src_vocab, tgt_vocab, units=512, num_layers=6,
                 num_heads=8, hidden_size=None, max_length=512,
                 dropout=0.1, attention_impl="dense", **kwargs):
        super().__init__(**kwargs)
        self._units = units
        with self.name_scope():
            self.src_embed_weight = self.params.get(
                "src_embed_weight", shape=(src_vocab, units),
                init="normal")
            self.tgt_embed_weight = self.params.get(
                "tgt_embed_weight", shape=(tgt_vocab, units),
                init="normal")
            self.position_embed_weight = self.params.get(
                "position_embed_weight", shape=(max_length, units),
                init="normal")
            self.encoder = TransformerEncoder(
                num_layers, units, num_heads, hidden_size, dropout,
                attention_impl, prefix="enc_")
            self.decoder = TransformerDecoder(
                num_layers, units, num_heads, hidden_size, dropout,
                attention_impl, prefix="dec_")
            self.out_proj = nn.Dense(tgt_vocab, in_units=units,
                                     flatten=False, prefix="out_")

    def hybrid_forward(self, F, src_tokens, tgt_tokens,
                       src_embed_weight, tgt_embed_weight,
                       position_embed_weight):
        scale = float(self._units) ** 0.5
        src = F.Embedding(src_tokens, src_embed_weight,
                          input_dim=src_embed_weight.shape[0],
                          output_dim=src_embed_weight.shape[1]) * scale
        tgt = F.Embedding(tgt_tokens, tgt_embed_weight,
                          input_dim=tgt_embed_weight.shape[0],
                          output_dim=tgt_embed_weight.shape[1]) * scale
        src = src + F.slice(position_embed_weight,
                            begin=(0, 0),
                            end=(src.shape[-2], None))
        tgt = tgt + F.slice(position_embed_weight,
                            begin=(0, 0),
                            end=(tgt.shape[-2], None))
        memory = self.encoder(src)
        dec = self.decoder(tgt, memory)
        return self.out_proj(dec)

    def greedy_decode(self, src_tokens, bos_id, eos_id, max_len=64):
        """Host-driven greedy decoding (reference analog: sockeye's
        inference loop)."""
        import numpy as np

        from ... import ndarray as nd

        B = src_tokens.shape[0]
        tgt = np.full((B, 1), bos_id, np.int32)
        finished = np.zeros(B, bool)
        for _ in range(max_len - 1):
            logits = self(src_tokens, nd.array(tgt.astype("float32")))
            nxt = logits.asnumpy()[:, -1].argmax(axis=-1).astype(np.int32)
            nxt = np.where(finished, eos_id, nxt)
            tgt = np.concatenate([tgt, nxt[:, None]], axis=1)
            finished |= nxt == eos_id
            if finished.all():
                break
        return tgt


class LabelSmoothedCELoss(HybridBlock):
    """Label-smoothed cross entropy (the NMT training loss; reference
    analog: sockeye/gluon-nlp label smoothing)."""

    def __init__(self, smoothing=0.1, ignore_index=-1, **kwargs):
        super().__init__(**kwargs)
        self._eps = smoothing
        self._ignore = ignore_index

    def hybrid_forward(self, F, logits, labels):
        from ...ndarray.register import invoke_simple

        eps, ignore = self._eps, self._ignore

        def pure(logits, labels):
            import jax
            import jax.numpy as jnp

            labels = labels.astype(jnp.int32)
            V = logits.shape[-1]
            logp = jax.nn.log_softmax(logits.astype(jnp.float32),
                                      axis=-1)
            valid = labels != ignore
            safe = jnp.maximum(labels, 0)
            nll = -jnp.take_along_axis(logp, safe[..., None],
                                       axis=-1)[..., 0]
            smooth = -jnp.mean(logp, axis=-1)
            loss = (1.0 - eps) * nll + eps * smooth
            denom = jnp.maximum(jnp.sum(valid), 1)
            return jnp.sum(jnp.where(valid, loss, 0.0)) / denom

        return invoke_simple(pure, (logits, labels))


def transformer_base(src_vocab, tgt_vocab, **kwargs):
    """'base' config of the original paper."""
    return Transformer(src_vocab, tgt_vocab, units=512, num_layers=6,
                       num_heads=8, hidden_size=2048, **kwargs)


def transformer_tiny(src_vocab, tgt_vocab, **kwargs):
    return Transformer(src_vocab, tgt_vocab, units=32, num_layers=2,
                       num_heads=2, hidden_size=64, **kwargs)


def beam_loop(score_last_fn, B, beam_size, init_token, eos_id,
              max_steps, alpha=0.6, seed_beams=None):
    """Generic length-normalized beam search core (GNMT length penalty).

    ``score_last_fn(flat_tokens (B·K, T)) -> (B·K, V)`` returns the
    LAST-position logits for each hypothesis.  Seeds either from a
    single ``init_token`` (encoder-decoder: BOS) or ``seed_beams``
    (B, T0) — a shared prompt per batch row (decoder-only LMs).  Both
    the NMT ``beam_search`` wrapper and ``gpt.beam_generate`` drive
    this one loop.  Returns (tokens (B, T), normalized scores (B,)).
    """
    import numpy as np

    K = beam_size
    if seed_beams is not None:
        beams = np.repeat(seed_beams[:, None, :], K, axis=1) \
            .astype(np.int32)
    else:
        beams = np.full((B, K, 1), init_token, np.int32)
    seed_len = beams.shape[2]
    scores = np.full((B, K), -1e9, np.float32)
    scores[:, 0] = 0.0  # only the first beam is live initially
    # a prompt already ending in EOS starts finished (free-EOS padding)
    finished = (beams[:, :, -1] == eos_id) if eos_id is not None \
        else np.zeros((B, K), bool)

    for _ in range(max_steps):
        flat = beams.reshape(B * K, -1)
        logp = score_last_fn(flat)
        logp = logp - _logsumexp(logp)  # normalize to log-probs
        V = logp.shape[-1]
        logp = logp.reshape(B, K, V)
        if eos_id is not None:
            # finished beams only extend with EOS at no cost
            logp = np.where(
                finished[:, :, None],
                np.where(np.arange(V)[None, None, :] == eos_id, 0.0,
                         -1e9),
                logp)
        total = scores[:, :, None] + logp               # (B, K, V)
        flat_total = total.reshape(B, K * V)
        top = np.argsort(-flat_total, axis=1)[:, :K]     # (B, K)
        new_scores = np.take_along_axis(flat_total, top, axis=1)
        src_beam = top // V
        tok = (top % V).astype(np.int32)
        beams = np.concatenate(
            [np.take_along_axis(beams, src_beam[:, :, None], axis=1),
             tok[:, :, None]], axis=2)
        if eos_id is not None:
            finished = np.take_along_axis(finished, src_beam, axis=1) \
                | (tok == eos_id)
        scores = new_scores
        if eos_id is not None and finished.all():
            break

    # GNMT length penalty on the FINAL scores — over GENERATED tokens
    # only (scores hold no seed-token log-probs, so counting the prompt
    # would neutralize the normalization for long prompts)
    gen = beams[:, :, seed_len:]
    if eos_id is not None:
        lengths = (gen != eos_id).sum(axis=2).astype(np.float32)
    else:
        lengths = np.full((B, K), gen.shape[2], np.float32)
    lp = ((5.0 + lengths) / 6.0) ** alpha
    normed = scores / lp
    best = normed.argmax(axis=1)
    out = beams[np.arange(B), best]
    return out, normed[np.arange(B), best]


def beam_search(model, src_tokens, bos_id, eos_id, beam_size=4,
                max_len=64, alpha=0.6):
    """Length-normalized beam search (reference analog: sockeye's
    inference; length penalty ((5+|Y|)/6)^alpha from GNMT).

    Host-driven loop over ``beam_loop``; each scoring step is one
    batched forward over B·beam hypotheses.  Returns
    (tokens (B, <=max_len), scores (B,)).
    """
    import numpy as np

    from ... import autograd
    from ... import ndarray as nd

    B = src_tokens.shape[0]
    src_np = src_tokens.asnumpy() if hasattr(src_tokens, "asnumpy") \
        else np.asarray(src_tokens)
    # tile sources per beam: (B*K, S)
    src_rep = nd.array(np.repeat(src_np, beam_size, axis=0))

    def score_last(flat):
        with autograd.predict_mode():
            logits = model(src_rep, nd.array(flat.astype("float32")))
        return logits.asnumpy()[:, -1]

    return beam_loop(score_last, B, beam_size, bos_id, eos_id,
                     max_len - 1, alpha)


def _logsumexp(a):
    import numpy as np

    m = a.max(axis=-1, keepdims=True)
    return m + np.log(np.exp(a - m).sum(axis=-1, keepdims=True))
