"""Transformer encoder / BERT model family.

Reference parity: the reference repo ships the transformer *kernels*
(src/operator/contrib/transformer.cu); the BERT model itself lives in
GluonNLP built on them (SURVEY.md §6 — the BASELINE tokens/sec/chip config).
This module provides the models natively, TPU-first:

- one packed QKV projection per layer (single MXU matmul);
- attention impl selectable per model: 'dense' (XLA), 'flash' (Pallas),
  'ring'/'ulysses' (sequence-parallel over the mesh sp axis);
- parameter names (qkv_weight, proj_weight, ffn1_weight, ffn2_weight,
  word_embed_weight) line up with parallel.TRANSFORMER_TP_RULES so the same
  model shards Megatron-style with zero model changes.
"""

from __future__ import annotations

import math

from ..block import HybridBlock
from .. import nn


class TransformerEncoderLayer(HybridBlock):
    """Pre-LN transformer encoder layer."""

    def __init__(self, units, num_heads, hidden_size=None, dropout=0.1,
                 attention_impl="dense", activation="gelu",
                 causal=False, **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        self._units = units
        self._num_heads = num_heads
        hidden_size = hidden_size or 4 * units
        self._attention_impl = attention_impl
        self._dropout = dropout
        self._activation = activation
        self._causal = causal
        with self.name_scope():
            self.qkv_weight = self.params.get("qkv_weight",
                                              shape=(3 * units, units))
            self.qkv_bias = self.params.get("qkv_bias", shape=(3 * units,),
                                            init="zeros")
            self.proj_weight = self.params.get("proj_weight",
                                               shape=(units, units))
            self.proj_bias = self.params.get("proj_bias", shape=(units,),
                                             init="zeros")
            self.ffn1_weight = self.params.get("ffn1_weight",
                                               shape=(hidden_size, units))
            self.ffn1_bias = self.params.get("ffn1_bias",
                                             shape=(hidden_size,),
                                             init="zeros")
            self.ffn2_weight = self.params.get("ffn2_weight",
                                               shape=(units, hidden_size))
            self.ffn2_bias = self.params.get("ffn2_bias", shape=(units,),
                                             init="zeros")
            self.ln1 = nn.LayerNorm(in_channels=units)
            self.ln2 = nn.LayerNorm(in_channels=units)
            if dropout:
                self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, qkv_weight, qkv_bias, proj_weight,
                       proj_bias, ffn1_weight, ffn1_bias, ffn2_weight,
                       ffn2_bias, mask=None):
        h = self.ln1(x)
        attn = F.multi_head_attention(
            h, h, h, qkv_weight=qkv_weight, qkv_bias=qkv_bias,
            proj_weight=proj_weight, proj_bias=proj_bias,
            num_heads=self._num_heads, mask=mask,
            impl=self._attention_impl, causal=self._causal)
        if self._dropout:
            attn = self.drop(attn)
        x = x + attn
        h = self.ln2(x)
        h = F.FullyConnected(h, ffn1_weight, ffn1_bias,
                             num_hidden=ffn1_weight.shape[0],
                             flatten=False)
        h = F.Activation(h, act_type=self._activation)
        h = F.FullyConnected(h, ffn2_weight, ffn2_bias,
                             num_hidden=ffn2_weight.shape[0],
                             flatten=False)
        if self._dropout:
            h = self.drop(h)
        return x + h


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, num_heads, hidden_size=None,
                 dropout=0.1, attention_impl="dense", causal=False,
                 **kwargs):
        super().__init__(**kwargs)
        self._num_layers = num_layers
        with self.name_scope():
            self.layers = nn.HybridSequential(prefix="")
            for i in range(num_layers):
                self.layers.add(TransformerEncoderLayer(
                    units, num_heads, hidden_size, dropout,
                    attention_impl, causal=causal,
                    prefix=f"layer{i}_"))
            self.ln_f = nn.LayerNorm(in_channels=units)

    def hybrid_forward(self, F, x):
        x = self.layers(x)
        return self.ln_f(x)


class ScanTransformerEncoder(HybridBlock):
    """Encoder trunk as ONE ``lax.scan`` over stacked per-layer params.

    TPU-first compile-time scalability: N separate layer blocks emit an
    HLO that grows linearly with depth (BERT-base whole-step compiles
    took tens of minutes through the AOT helper); scanning a single
    layer body over (L, ...) parameter stacks compiles the layer once.
    Numerics match TransformerEncoder exactly (same pre-LN math, same
    packed-qkv MHA op) — equivalence-tested in tests/test_model_zoo.py.

    Stacked params use ``*_stack_*`` names so TP rules shard dim 1+
    (the layer dim stays unsharded); see TRANSFORMER_TP_RULES.
    """

    def __init__(self, num_layers, units, num_heads, hidden_size=None,
                 dropout=0.1, attention_impl="dense",
                 activation="gelu", remat=False, causal=False,
                 lora_rank=0, lora_alpha=None, **kwargs):
        super().__init__(**kwargs)
        assert units % num_heads == 0
        hidden_size = hidden_size or 4 * units
        self._remat = bool(remat)
        self._causal = causal
        self._num_layers = num_layers
        self._units = units
        self._num_heads = num_heads
        self._hidden = hidden_size
        self._dropout = dropout
        self._attention_impl = attention_impl
        self._activation = activation
        self._lora_rank = int(lora_rank)
        # default alpha = 2·rank → scale 2.0, matching LoRADense's
        # default (alpha=16 at rank 8) — hyperparameters port between
        # the two surfaces unchanged
        if lora_rank:
            alpha = (float(lora_alpha) if lora_alpha is not None
                     else 2.0 * lora_rank)
            self._lora_scale = alpha / lora_rank
        else:
            self._lora_scale = 0.0
        L, u, h = num_layers, units, hidden_size
        with self.name_scope():
            self.qkv_stack_weight = self.params.get(
                "qkv_stack_weight", shape=(L, 3 * u, u))
            self.qkv_stack_bias = self.params.get(
                "qkv_stack_bias", shape=(L, 3 * u), init="zeros")
            self.proj_stack_weight = self.params.get(
                "proj_stack_weight", shape=(L, u, u))
            self.proj_stack_bias = self.params.get(
                "proj_stack_bias", shape=(L, u), init="zeros")
            self.ffn1_stack_weight = self.params.get(
                "ffn1_stack_weight", shape=(L, h, u))
            self.ffn1_stack_bias = self.params.get(
                "ffn1_stack_bias", shape=(L, h), init="zeros")
            self.ffn2_stack_weight = self.params.get(
                "ffn2_stack_weight", shape=(L, u, h))
            self.ffn2_stack_bias = self.params.get(
                "ffn2_stack_bias", shape=(L, u), init="zeros")
            self.ln1_stack_gamma = self.params.get(
                "ln1_stack_gamma", shape=(L, u), init="ones")
            self.ln1_stack_beta = self.params.get(
                "ln1_stack_beta", shape=(L, u), init="zeros")
            self.ln2_stack_gamma = self.params.get(
                "ln2_stack_gamma", shape=(L, u), init="ones")
            self.ln2_stack_beta = self.params.get(
                "ln2_stack_beta", shape=(L, u), init="zeros")
            self.lnf_gamma = self.params.get("lnf_gamma", shape=(u,),
                                             init="ones")
            self.lnf_beta = self.params.get("lnf_beta", shape=(u,),
                                            init="zeros")
            if self._lora_rank:
                r = self._lora_rank
                # zero-init B: the adapted trunk starts EXACTLY equal
                # to the base; names avoid the *_stack_weight TP-rule
                # suffixes (tiny adapters stay replicated)
                self.qkv_lora_a = self.params.get(
                    "qkv_lora_a", shape=(L, r, u), init="normal")
                self.qkv_lora_b = self.params.get(
                    "qkv_lora_b", shape=(L, 3 * u, r), init="zeros")

    def hybrid_forward(self, F, x, qkv_stack_weight, qkv_stack_bias,
                       proj_stack_weight, proj_stack_bias,
                       ffn1_stack_weight, ffn1_stack_bias,
                       ffn2_stack_weight, ffn2_stack_bias,
                       ln1_stack_gamma, ln1_stack_beta,
                       ln2_stack_gamma, ln2_stack_beta,
                       lnf_gamma, lnf_beta, qkv_lora_a=None,
                       qkv_lora_b=None):
        kw = {}
        if qkv_lora_a is not None:
            kw = {"qkv_lora_a": qkv_lora_a, "qkv_lora_b": qkv_lora_b,
                  "lora_scale": self._lora_scale}
        return F.scan_transformer_encoder(
            x, qkv_stack_weight, qkv_stack_bias, proj_stack_weight,
            proj_stack_bias, ffn1_stack_weight, ffn1_stack_bias,
            ffn2_stack_weight, ffn2_stack_bias, ln1_stack_gamma,
            ln1_stack_beta, ln2_stack_gamma, ln2_stack_beta,
            lnf_gamma, lnf_beta, num_heads=self._num_heads,
            dropout=self._dropout, activation=self._activation,
            impl=self._attention_impl, causal=self._causal,
            remat=self._remat, **kw)


class BERTModel(HybridBlock):
    """BERT encoder with MLM + NSP heads (BASELINE: tokens/sec/chip
    pretrain config)."""

    def __init__(self, vocab_size=30522, units=768, num_layers=12,
                 num_heads=12, hidden_size=3072, max_length=512,
                 token_types=2, dropout=0.1, attention_impl="dense",
                 use_pooler=True, use_decoder=True, use_classifier=True,
                 scan_layers=False, lora_rank=0, lora_alpha=None,
                 **kwargs):
        super().__init__(**kwargs)
        if lora_rank and not scan_layers:
            raise ValueError("BERTModel: lora_rank requires "
                             "scan_layers=True (adapters live in the "
                             "scanned trunk)")
        self._units = units
        self._use_pooler = use_pooler
        self._use_decoder = use_decoder
        self._use_classifier = use_classifier
        with self.name_scope():
            self.word_embed_weight = self.params.get(
                "word_embed_weight", shape=(vocab_size, units),
                init="normal")
            self.token_type_embed_weight = self.params.get(
                "token_type_embed_weight", shape=(token_types, units),
                init="normal")
            self.position_embed_weight = self.params.get(
                "position_embed_weight", shape=(max_length, units),
                init="normal")
            self.embed_ln = nn.LayerNorm(in_channels=units)
            if dropout:
                self.embed_drop = nn.Dropout(dropout)
            self._dropout = dropout
            if scan_layers:
                self.encoder = ScanTransformerEncoder(
                    num_layers, units, num_heads, hidden_size, dropout,
                    attention_impl, lora_rank=lora_rank,
                    lora_alpha=lora_alpha, prefix="enc_")
            else:
                self.encoder = TransformerEncoder(
                    num_layers, units, num_heads, hidden_size, dropout,
                    attention_impl, prefix="enc_")
            if use_pooler:
                self.pooler = nn.Dense(units, activation="tanh",
                                       in_units=units, prefix="pooler_")
            if use_decoder:
                # MLM head: transform + tied-embedding decode
                self.decoder_transform = nn.Dense(
                    units, activation="gelu", in_units=units,
                    flatten=False, prefix="dec_transform_")
                self.decoder_ln = nn.LayerNorm(in_channels=units)
                self.decoder_bias = self.params.get(
                    "decoder_bias", shape=(vocab_size,), init="zeros")
            if use_classifier:
                self.nsp_classifier = nn.Dense(2, in_units=units,
                                               prefix="nsp_")

    def hybrid_forward(self, F, inputs, token_types=None,
                       word_embed_weight=None, token_type_embed_weight=None,
                       position_embed_weight=None, decoder_bias=None):
        T = inputs.shape[1]
        x = F.Embedding(inputs, word_embed_weight)
        if token_types is not None:
            x = x + F.Embedding(token_types, token_type_embed_weight)
        else:
            # [0:1] not [0]: a slice broadcasts identically eagerly AND
            # traces as array indexing (bare ints mean output views on
            # Symbols)
            x = x + token_type_embed_weight[0:1]
        x = x + position_embed_weight[:T]
        x = self.embed_ln(x)
        if self._dropout:
            x = self.embed_drop(x)
        seq = self.encoder(x)  # (B, T, C)
        outputs = [seq]
        if self._use_pooler:
            pooled = self.pooler(seq[:, 0, :])
            outputs.append(pooled)
            if self._use_classifier:
                outputs.append(self.nsp_classifier(pooled))
        if self._use_decoder:
            h = self.decoder_transform(seq)
            h = self.decoder_ln(h)
            logits = F.FullyConnected(
                h, word_embed_weight, decoder_bias,
                num_hidden=word_embed_weight.shape[0], flatten=False)
            outputs.append(logits)
        return tuple(outputs) if len(outputs) > 1 else outputs[0]


def masked_token_ce(logits, labels):
    """Mean token cross-entropy over valid (label >= 0) positions — the
    ONE masked-CE implementation (BERTMLMLoss, the pretrain loss and
    gpt.GPTLMLoss all delegate here)."""
    import jax
    import jax.numpy as jnp

    labels = labels.astype(jnp.int32)
    valid = labels >= 0
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(
        logp, jnp.maximum(labels, 0)[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(jnp.sum(valid), 1)
    return jnp.sum(jnp.where(valid, nll, 0.0)) / denom


def _bert_pretrain_loss_pure(nsp_logits, mlm_logits, mlm_labels,
                             nsp_labels):
    import jax
    import jax.numpy as jnp

    mlm_loss = masked_token_ce(mlm_logits, mlm_labels)
    nsp_logp = jax.nn.log_softmax(
        nsp_logits.astype(jnp.float32), axis=-1)
    nsp_nll = -jnp.take_along_axis(
        nsp_logp, nsp_labels.astype(jnp.int32)[:, None], axis=-1)[:, 0]
    return mlm_loss + jnp.mean(nsp_nll)


class BERTPretrainLoss(HybridBlock):
    """MLM + NSP loss over BERTModel outputs (masked-position MLM).

    outputs: (seq, pooled, nsp_logits, mlm_logits); labels: (mlm_labels
    (B,T) with -1 for unmasked positions, nsp_labels (B,)).  Routed
    through the invoke layer: one tape node eagerly, pure under jit."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def hybrid_forward(self, F, outputs, labels):
        from ...ndarray.register import invoke_simple

        seq, pooled, nsp_logits, mlm_logits = outputs
        mlm_labels, nsp_labels = labels
        return invoke_simple(_bert_pretrain_loss_pure,
                             (nsp_logits, mlm_logits, mlm_labels,
                              nsp_labels))


class BERTEmbedding(HybridBlock):
    """Token + type + position embedding front (the pipeline prologue).

    The prologue takes token ids only, so the token-type table holds just
    segment 0 — shape (1, units), an additive bias; a bigger table would
    be dead trainable parameters in the pipeline's replicated group."""

    def __init__(self, vocab_size=30522, units=768, max_length=512,
                 dropout=0.0, **kwargs):
        super().__init__(**kwargs)
        self._dropout = dropout
        with self.name_scope():
            self.word_embed_weight = self.params.get(
                "word_embed_weight", shape=(vocab_size, units),
                init="normal")
            self.token_type_embed_weight = self.params.get(
                "token_type_embed_weight", shape=(1, units),
                init="normal")
            self.position_embed_weight = self.params.get(
                "position_embed_weight", shape=(max_length, units),
                init="normal")
            self.embed_ln = nn.LayerNorm(in_channels=units)
            if dropout:
                self.embed_drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, inputs, word_embed_weight=None,
                       token_type_embed_weight=None,
                       position_embed_weight=None):
        T = inputs.shape[1]
        x = F.Embedding(inputs, word_embed_weight)
        x = x + token_type_embed_weight[0:1]  # slice: trace-safe
        x = x + position_embed_weight[:T]
        x = self.embed_ln(x)
        if self._dropout:
            x = self.embed_drop(x)
        return x


class BERTMLMHead(HybridBlock):
    """Transform + decode-to-vocab head (the pipeline epilogue).  The
    decode weight is untied here (pipeline stages own disjoint params)."""

    def __init__(self, vocab_size=30522, units=768, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.transform = nn.Dense(units, activation="gelu",
                                      in_units=units, flatten=False,
                                      prefix="transform_")
            self.ln = nn.LayerNorm(in_channels=units)
            self.decoder = nn.Dense(vocab_size, in_units=units,
                                    flatten=False, prefix="decoder_")

    def hybrid_forward(self, F, x):
        return self.decoder(self.ln(self.transform(x)))


class BERTMLMLoss(HybridBlock):
    """Masked-LM cross entropy over head logits; labels (B, T), -1 at
    unmasked positions."""

    def hybrid_forward(self, F, logits, labels):
        from ...ndarray.register import invoke_simple

        return invoke_simple(masked_token_ce, (logits, labels))


def bert_pipeline_parts(vocab_size=30522, units=768, num_layers=12,
                        num_heads=12, hidden_size=None, max_length=512,
                        dropout=0.0, attention_impl="dense"):
    """(prologue, trunk stages, epilogue) for parallel.PipelineTrainer:
    a full BERT as embedding + homogeneous encoder layers + MLM head."""
    embed = BERTEmbedding(vocab_size=vocab_size, units=units,
                          max_length=max_length, dropout=dropout,
                          prefix="ppembed_")
    layers = [TransformerEncoderLayer(
        units, num_heads, hidden_size or 4 * units, dropout,
        attention_impl, prefix=f"pplayer{i}_") for i in range(num_layers)]
    head = BERTMLMHead(vocab_size=vocab_size, units=units,
                       prefix="pphead_")
    return embed, layers, head


def bert_base(**kwargs):
    return BERTModel(units=768, num_layers=12, num_heads=12,
                     hidden_size=3072, **kwargs)


def bert_large(**kwargs):
    return BERTModel(units=1024, num_layers=24, num_heads=16,
                     hidden_size=4096, **kwargs)


def bert_tiny(**kwargs):
    """Testing-scale config."""
    kwargs.setdefault("vocab_size", 1024)
    kwargs.setdefault("max_length", 128)
    return BERTModel(units=64, num_layers=2, num_heads=4,
                     hidden_size=128, **kwargs)
