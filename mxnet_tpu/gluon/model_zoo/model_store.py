"""Pretrained-weight store (reference:
python/mxnet/gluon/model_zoo/model_store.py).

Zero-egress environment: weights resolve only from the local root
(~/.mxnet/models); a missing file is a clear error instead of a download.
Files saved by the reference (`.params`, the NDArray container format) load
directly — the serialization layer is byte-compatible.
"""

from __future__ import annotations

import os

from ...base import MXNetError


def get_model_file(name, root=os.path.join("~", ".mxnet", "models")):
    """Locate a pretrained parameter file locally."""
    root = os.path.expanduser(root)
    file_path = os.path.join(root, f"{name}.params")
    if os.path.exists(file_path):
        return file_path
    candidates = []
    if os.path.isdir(root):
        candidates = [f for f in os.listdir(root)
                      if f.startswith(name) and f.endswith(".params")]
    if candidates:
        return os.path.join(root, sorted(candidates)[-1])
    raise MXNetError(
        f"Pretrained model file for '{name}' not found under {root}. This "
        "environment has no network access; place the .params file there "
        "manually (reference-format files are compatible).")


def purge(root=os.path.join("~", ".mxnet", "models")):
    root = os.path.expanduser(root)
    if not os.path.isdir(root):
        return
    for f in os.listdir(root):
        if f.endswith(".params"):
            os.remove(os.path.join(root, f))
