"""Model zoo (reference: python/mxnet/gluon/model_zoo/)."""

from . import vision
from . import bert
from . import gpt
from . import ssd
from .ssd import SSD, SSDTrainLoss, ssd_detect
from .bert import (BERTModel, BERTPretrainLoss, TransformerEncoder,
                   TransformerEncoderLayer, bert_base, bert_large,
                   bert_tiny)
from .gpt import (GPTModel, GPTLMLoss, gpt2_small, gpt2_medium,
                  gpt_tiny, CachedDecoder, speculative_decode)
from .model_store import get_model_file, purge
from . import transformer
