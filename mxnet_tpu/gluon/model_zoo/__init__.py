"""Model zoo (reference: python/mxnet/gluon/model_zoo/)."""

from . import vision
from .model_store import get_model_file, purge
