"""Decoder-only (GPT-style) language model family.

Beyond-reference breadth: the 2018-era reference zoo has no decoder-only
LM (its nearest is example/rnn word_lm and the NMT Transformer decoder);
this family completes the transformer spread — encoder (BERT),
encoder-decoder (transformer.py NMT), decoder-only (here) — on the same
TPU-first trunk primitives:

- causal attention via the SAME packed-qkv MHA op (flash/ring/ulysses
  ``attention_impl`` all apply — the long-context causal config);
- ``scan_layers=True`` compiles the trunk as one scanned layer
  (compile-time scalability, same as BERT's bench config);
- the LM head is WEIGHT-TIED to the token embedding (standard GPT-2
  parameterization): one (vocab, units) matrix serves both.
"""

from __future__ import annotations

from ..block import HybridBlock
from .. import nn
from .bert import ScanTransformerEncoder, TransformerEncoder


class GPTModel(HybridBlock):
    """Token+position embedding → causal pre-LN trunk → tied-head
    logits.  Input: (B, T) int token ids; output: (B, T, vocab)."""

    def __init__(self, vocab_size=50257, units=768, num_layers=12,
                 num_heads=12, max_length=1024, hidden_size=None,
                 dropout=0.1, attention_impl="dense", scan_layers=False,
                 remat=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._max_length = max_length
        self._dropout = dropout
        with self.name_scope():
            self.tok_embed_weight = self.params.get(
                "tok_embed_weight", shape=(vocab_size, units))
            self.pos_embed_weight = self.params.get(
                "pos_embed_weight", shape=(max_length, units))
            if scan_layers:
                self.encoder = ScanTransformerEncoder(
                    num_layers, units, num_heads, hidden_size, dropout,
                    attention_impl, causal=True, remat=remat,
                    prefix="trunk_")
            else:
                self.encoder = TransformerEncoder(
                    num_layers, units, num_heads, hidden_size, dropout,
                    attention_impl, causal=True, prefix="trunk_")
            if dropout:
                self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, ids, tok_embed_weight,
                       pos_embed_weight):
        x = F.Embedding(ids, tok_embed_weight,
                        input_dim=tok_embed_weight.shape[0],
                        output_dim=self._units)
        T = ids.shape[1]
        x = x + F.slice_axis(pos_embed_weight, axis=0, begin=0, end=T)
        if self._dropout:
            x = self.drop(x)
        h = self.encoder(x)                       # (B, T, C)
        # tied head: logits = h @ embedᵀ — one big MXU matmul
        return F.dot(F.reshape(h, (-1, self._units)), tok_embed_weight,
                     transpose_b=True).reshape(
            (ids.shape[0], T, tok_embed_weight.shape[0]))


def _lm_loss_pure(logits, labels):
    """Shifted next-token cross-entropy; labels < 0 are ignored —
    the shift plus the zoo's shared masked-CE."""
    from .bert import masked_token_ce

    return masked_token_ce(logits[:, :-1], labels[:, 1:])


class GPTLMLoss(HybridBlock):
    """Causal LM loss: mean next-token NLL over valid (>= 0) labels.
    Call with (logits, token_ids) — the shift happens inside."""

    def hybrid_forward(self, F, logits, labels):
        from ...ndarray.register import invoke_simple

        return invoke_simple(_lm_loss_pure, (logits, labels))


def generate(model, ids, max_new_tokens=16, temperature=None, rng=None):
    """Greedy (or sampled) decode by full-recompute per step — the
    simple deploy path; ids: (B, T0) NDArray of seed tokens.

    The context is RIGHT-padded to max_length so every step runs at ONE
    shape (one compile, critical on the slow-AOT TPU tunnel); causal
    masking makes positions > cur-1 invisible to the read position, so
    the pad content never matters."""
    import numpy as np

    from ... import ndarray as nd

    out = ids.asnumpy().astype(np.int32)
    W = model._max_length
    for _ in range(max_new_tokens):
        ctx = out[:, -W:]
        cur = ctx.shape[1]
        if cur < W:
            ctx = np.concatenate(
                [ctx, np.zeros((ctx.shape[0], W - cur), np.int32)],
                axis=1)
        logits = model(nd.array(ctx.astype(np.float32))).asnumpy()
        last = logits[:, cur - 1]
        if temperature:
            z = last / temperature
            z = z - z.max(axis=-1, keepdims=True)
            p = np.exp(z) / np.exp(z).sum(axis=-1, keepdims=True)
            rng = rng or np.random.default_rng()
            nxt = np.stack([rng.choice(p.shape[-1], p=row)
                            for row in p])
        else:
            nxt = last.argmax(axis=-1)
        out = np.concatenate([out, nxt[:, None].astype(np.int32)],
                             axis=1)
    return nd.array(out.astype(np.float32))


def gpt2_small(**kwargs):
    """GPT-2 124M config."""
    kwargs.setdefault("vocab_size", 50257)
    kwargs.setdefault("units", 768)
    kwargs.setdefault("num_layers", 12)
    kwargs.setdefault("num_heads", 12)
    kwargs.setdefault("max_length", 1024)
    return GPTModel(**kwargs)


def gpt2_medium(**kwargs):
    kwargs.setdefault("vocab_size", 50257)
    kwargs.setdefault("units", 1024)
    kwargs.setdefault("num_layers", 24)
    kwargs.setdefault("num_heads", 16)
    kwargs.setdefault("max_length", 1024)
    return GPTModel(**kwargs)


def gpt_tiny(**kwargs):
    """Test-sized config."""
    kwargs.setdefault("vocab_size", 128)
    kwargs.setdefault("units", 32)
    kwargs.setdefault("num_layers", 2)
    kwargs.setdefault("num_heads", 2)
    kwargs.setdefault("max_length", 64)
    kwargs.setdefault("dropout", 0.0)
    return GPTModel(**kwargs)
