"""Decoder-only (GPT-style) language model family.

Beyond-reference breadth: the 2018-era reference zoo has no decoder-only
LM (its nearest is example/rnn word_lm and the NMT Transformer decoder);
this family completes the transformer spread — encoder (BERT),
encoder-decoder (transformer.py NMT), decoder-only (here) — on the same
TPU-first trunk primitives:

- causal attention via the SAME packed-qkv MHA op (flash/ring/ulysses
  ``attention_impl`` all apply — the long-context causal config);
- ``scan_layers=True`` compiles the trunk as one scanned layer
  (compile-time scalability, same as BERT's bench config);
- the LM head is WEIGHT-TIED to the token embedding (standard GPT-2
  parameterization): one (vocab, units) matrix serves both.
"""

from __future__ import annotations

from ..block import HybridBlock
from .. import nn
from .bert import ScanTransformerEncoder, TransformerEncoder


class GPTModel(HybridBlock):
    """Token+position embedding → causal pre-LN trunk → tied-head
    logits.  Input: (B, T) int token ids; output: (B, T, vocab)."""

    def __init__(self, vocab_size=50257, units=768, num_layers=12,
                 num_heads=12, max_length=1024, hidden_size=None,
                 dropout=0.1, attention_impl="dense", scan_layers=False,
                 remat=False, lora_rank=0, lora_alpha=None, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._vocab = vocab_size
        self._max_length = max_length
        self._dropout = dropout
        if lora_rank and not scan_layers:
            raise ValueError("GPTModel: lora_rank requires "
                             "scan_layers=True (adapters live in the "
                             "scanned trunk)")
        with self.name_scope():
            self.tok_embed_weight = self.params.get(
                "tok_embed_weight", shape=(vocab_size, units))
            self.pos_embed_weight = self.params.get(
                "pos_embed_weight", shape=(max_length, units))
            if scan_layers:
                self.encoder = ScanTransformerEncoder(
                    num_layers, units, num_heads, hidden_size, dropout,
                    attention_impl, causal=True, remat=remat,
                    lora_rank=lora_rank, lora_alpha=lora_alpha,
                    prefix="trunk_")
            else:
                self.encoder = TransformerEncoder(
                    num_layers, units, num_heads, hidden_size, dropout,
                    attention_impl, causal=True, prefix="trunk_")
            if dropout:
                self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, ids, tok_embed_weight,
                       pos_embed_weight):
        # stored sizes keep the op attrs static ints under the trace
        x = F.Embedding(ids, tok_embed_weight, input_dim=self._vocab,
                        output_dim=self._units)
        T = ids.shape[1]
        x = x + F.slice_axis(pos_embed_weight, axis=0, begin=0, end=T)
        if self._dropout:
            x = self.drop(x)
        h = self.encoder(x)                       # (B, T, C)
        # tied head: logits = h @ embedᵀ — one big MXU matmul
        # (kwarg shape= so the symbolic trace maps it as an attribute)
        flat = F.reshape(h, shape=(-1, self._units))
        logits = F.dot(flat, tok_embed_weight, transpose_b=True)
        return F.reshape(logits, shape=(-1, T, self._vocab))


def _lm_loss_pure(logits, labels):
    """Shifted next-token cross-entropy; labels < 0 are ignored —
    the shift plus the zoo's shared masked-CE."""
    from .bert import masked_token_ce

    return masked_token_ce(logits[:, :-1], labels[:, 1:])


class GPTLMLoss(HybridBlock):
    """Causal LM loss: mean next-token NLL over valid (>= 0) labels.
    Call with (logits, token_ids) — the shift happens inside."""

    def hybrid_forward(self, F, logits, labels):
        from ...ndarray.register import invoke_simple

        return invoke_simple(_lm_loss_pure, (logits, labels))


def _windowed_last_logits(model, flat, nd_mod, np_mod):
    """Last-position logits for (N, T) token rows through the model's
    fixed max_length window: right-pad to W (one compiled shape — causal
    masking hides the pad) and read position cur-1.  Shared by
    generate() and beam_generate()."""
    W = model._max_length
    ctx = flat[:, -W:]
    cur = ctx.shape[1]
    if cur < W:
        ctx = np_mod.concatenate(
            [ctx, np_mod.zeros((ctx.shape[0], W - cur), np_mod.int32)],
            axis=1)
    logits = model(nd_mod.array(ctx.astype(np_mod.float32))).asnumpy()
    return logits[:, cur - 1]


def _sample(last, temperature, rng):
    """Pick next tokens from (B, vocab) logits: greedy, or softmax
    sampling at the given temperature (one home for both decode paths)."""
    import numpy as np

    if temperature:
        z = last / temperature
        z = z - z.max(axis=-1, keepdims=True)
        p = np.exp(z) / np.exp(z).sum(axis=-1, keepdims=True)
        rng = rng or np.random.default_rng()
        return np.stack([rng.choice(p.shape[-1], p=row)
                         for row in p]).astype(np.int32)
    return last.argmax(axis=-1).astype(np.int32)


def generate(model, ids, max_new_tokens=16, temperature=None, rng=None):
    """Greedy (or sampled) decode by full-recompute per step — the
    simple deploy path; ids: (B, T0) NDArray of seed tokens.

    The context is RIGHT-padded to max_length so every step runs at ONE
    shape (one compile, critical on the slow-AOT TPU tunnel); causal
    masking makes positions > cur-1 invisible to the read position, so
    the pad content never matters."""
    import numpy as np

    from ... import ndarray as nd

    out = ids.asnumpy().astype(np.int32)
    for _ in range(max_new_tokens):
        last = _windowed_last_logits(model, out, nd, np)
        nxt = _sample(last, temperature, rng)
        out = np.concatenate([out, nxt[:, None]], axis=1)
    return nd.array(out.astype(np.float32))


def gpt2_small(**kwargs):
    """GPT-2 124M config."""
    kwargs.setdefault("vocab_size", 50257)
    kwargs.setdefault("units", 768)
    kwargs.setdefault("num_layers", 12)
    kwargs.setdefault("num_heads", 12)
    kwargs.setdefault("max_length", 1024)
    return GPTModel(**kwargs)


def gpt2_medium(**kwargs):
    kwargs.setdefault("vocab_size", 50257)
    kwargs.setdefault("units", 1024)
    kwargs.setdefault("num_layers", 24)
    kwargs.setdefault("num_heads", 16)
    kwargs.setdefault("max_length", 1024)
    return GPTModel(**kwargs)


def gpt_tiny(**kwargs):
    """Test-sized config."""
    kwargs.setdefault("vocab_size", 128)
    kwargs.setdefault("units", 32)
    kwargs.setdefault("num_layers", 2)
    kwargs.setdefault("num_heads", 2)
    kwargs.setdefault("max_length", 64)
    kwargs.setdefault("dropout", 0.0)
    return GPTModel(**kwargs)


# -- KV-cache incremental decoding ---------------------------------------------
#
# TPU-native inference engine for the decoder-only family: a STATIC
# (L, B, H, W, Dh) key/value cache updated with dynamic_update_slice at
# a traced position, so the per-token step is ONE compiled program doing
# O(W) attention instead of recomputing the O(W²) trunk (the role the
# reference's inference-time BucketingModule/exec cache plays for RNNs).


#: the scanned-trunk parameter stacks every cached/serving decoder runs on
STACK_NAMES = ("qkv_stack_weight", "qkv_stack_bias",
               "proj_stack_weight", "proj_stack_bias",
               "ffn1_stack_weight", "ffn1_stack_bias",
               "ffn2_stack_weight", "ffn2_stack_bias",
               "ln1_stack_gamma", "ln1_stack_beta",
               "ln2_stack_gamma", "ln2_stack_beta")


def extract_decoder_stacks(model):
    """Pull a GPTModel's trunk parameters into (L, ...) stacks, for scan
    and unstacked trunks alike.  Returns
    ``(stacks, (lnf_gamma, lnf_beta), tok_embed, pos_embed, num_heads,
    activation)`` — the single weight-extraction home shared by
    CachedDecoder and the serving tier (mxnet_tpu/serving/engine.py),
    so both consume the exact same layout."""
    params = dict(model.collect_params())

    def get1(suffix):
        ks = [k for k in params if k.endswith(suffix)]
        assert len(ks) == 1, (suffix, ks)
        return params[ks[0]].data()._data

    if any(k.endswith("qkv_stack_weight") for k in params):
        stacks = {nm: get1(nm) for nm in STACK_NAMES}
        lnf_g, lnf_b = get1("lnf_gamma"), get1("lnf_beta")
        num_heads = model.encoder._num_heads
        act = model.encoder._activation
    else:
        enc = model.encoder
        layers = list(enc.layers._children.values())
        num_heads = layers[0]._num_heads
        act = layers[0]._activation

        def stacked(name):
            import jax.numpy as jnp

            return jnp.stack([
                getattr(l, name).data()._data for l in layers])

        stacks = {
            "qkv_stack_weight": stacked("qkv_weight"),
            "qkv_stack_bias": stacked("qkv_bias"),
            "proj_stack_weight": stacked("proj_weight"),
            "proj_stack_bias": stacked("proj_bias"),
            "ffn1_stack_weight": stacked("ffn1_weight"),
            "ffn1_stack_bias": stacked("ffn1_bias"),
            "ffn2_stack_weight": stacked("ffn2_weight"),
            "ffn2_stack_bias": stacked("ffn2_bias"),
        }
        import jax.numpy as jnp

        stacks["ln1_stack_gamma"] = jnp.stack(
            [l.ln1.gamma.data()._data for l in layers])
        stacks["ln1_stack_beta"] = jnp.stack(
            [l.ln1.beta.data()._data for l in layers])
        stacks["ln2_stack_gamma"] = jnp.stack(
            [l.ln2.gamma.data()._data for l in layers])
        stacks["ln2_stack_beta"] = jnp.stack(
            [l.ln2.beta.data()._data for l in layers])
        lnf_g = enc.ln_f.gamma.data()._data
        lnf_b = enc.ln_f.beta.data()._data

    return (stacks, (lnf_g, lnf_b), get1("tok_embed_weight"),
            get1("pos_embed_weight"), num_heads, act)


class CachedDecoder:
    """Wraps a GPTModel into jitted prefill/step functions.

    Works for scan and unstacked trunks alike: parameters are pulled
    into (L, ...) stacks once at construction.  ``decode`` mirrors
    ``generate``'s sampling surface but runs the cached path.

    Pass ``mesh=`` (with a ``tp_axis`` mesh axis) for tensor-parallel
    serving: heads, the KV cache, and the FFN hidden dim shard over the
    axis (Megatron column/row rules) and GSPMD inserts the two
    per-layer all-reduces — multi-chip decode with no code change.
    """

    def __init__(self, model, mesh=None, tp_axis="tp", dtype=None):
        self._W = model._max_length
        self._mesh = mesh
        self._tp_axis = tp_axis
        self._dtype = dtype
        (stacks, (lnf_g, lnf_b), tok, pos,
         num_heads, act) = extract_decoder_stacks(model)
        self._stacks = stacks
        self._lnf = (lnf_g, lnf_b)
        self._tok = tok
        self._pos = pos
        if dtype is not None:
            # Serving precision: the BIG tensors (weight stacks, embed
            # tables, and — via self._tok.dtype — the KV cache) go
            # bf16 in HBM; LN/bias params and all accumulations stay
            # f32 (jnp promotion), so this halves the HBM traffic the
            # bandwidth-bound decode step is limited by without
            # touching the numerics-sensitive reductions.
            for nm in ("qkv_stack_weight", "proj_stack_weight",
                       "ffn1_stack_weight", "ffn2_stack_weight"):
                self._stacks[nm] = self._stacks[nm].astype(dtype)
            self._tok = self._tok.astype(dtype)
            self._pos = self._pos.astype(dtype)
        self._H = num_heads
        self._act = act
        self._step_fn = None

    def _shard(self, arr, spec):
        """Place with a NamedSharding when a tp mesh is set (GSPMD then
        propagates the layout and inserts the collectives); no-op on the
        single-device path."""
        if self._mesh is None:
            return arr
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(arr, NamedSharding(self._mesh, P(*spec)))

    def _init_cache(self, B):
        """Fresh zeroed (ck, cv) for batch B, with the serving dtype
        and (when a tp mesh is set) the head-sharded layout."""
        import jax.numpy as jnp

        L = self._stacks["qkv_stack_weight"].shape[0]
        Dh = self._tok.shape[1] // self._H
        spec = (None, None, self._tp_axis, None, None)
        shape = (L, B, self._H, self._W, Dh)
        return (self._shard(jnp.zeros(shape, self._tok.dtype), spec),
                self._shard(jnp.zeros(shape, self._tok.dtype), spec))

    def _build(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ...ops.nn import layer_norm

        H, W = self._H, self._W
        tok_e, pos_e = self._tok, self._pos
        lnf_g, lnf_b = self._lnf
        s = self._stacks
        C = tok_e.shape[1]
        Dh = C // H
        act = self._act
        L = s["qkv_stack_weight"].shape[0]
        F = s["ffn1_stack_weight"].shape[1]
        tp = self._tp_axis
        if self._mesh is not None:
            n_tp = self._mesh.shape[tp]
            if H % n_tp or F % n_tp:
                raise ValueError(
                    f"CachedDecoder: tp axis size {n_tp} must divide "
                    f"both num_heads={H} and ffn hidden={F}")

        # Head-/hidden-major restructuring so a tp mesh shards the H and
        # F dims (Megatron rules: column-parallel qkv/ffn1, row-parallel
        # proj/ffn2 — the contraction over a sharded dim becomes XLA's
        # all-reduce).  Single-device runs the same code unsharded.
        qkvw = self._shard(
            s["qkv_stack_weight"].reshape(L, 3, H, Dh, C),
            (None, None, tp))
        qkvb = self._shard(s["qkv_stack_bias"].reshape(L, 3, H, Dh),
                           (None, None, tp))
        pwh = self._shard(s["proj_stack_weight"].reshape(L, C, H, Dh),
                          (None, None, tp))
        f1w = self._shard(s["ffn1_stack_weight"], (None, tp))
        f1b = self._shard(s["ffn1_stack_bias"], (None, tp))
        f2w = self._shard(s["ffn2_stack_weight"], (None, None, tp))
        pb, f2b = s["proj_stack_bias"], s["ffn2_stack_bias"]

        def step(ck, cv, pos, toks):
            """Block step: ck/cv (L, B, H, W, Dh); pos scalar (write
            offset); toks (B, S) int32 — S tokens processed in one
            causal pass (S=1 is the classic per-token step; S=T0 is
            chunked prefill; S=k verifies a speculative draft block).
            Returns (new_ck, new_cv, logits (B, S, vocab))."""
            S = toks.shape[1]
            # residual stream in f32 regardless of the serving dtype
            x = (jnp.take(tok_e, toks, axis=0) +
                 lax.dynamic_slice(pos_e, (pos, 0), (S, C))[None]
                 ).astype(jnp.float32)                        # (B, S, C)

            def layer(x, per):
                (qw, qb, pw, pb, f1w, f1b, f2w, f2b, g1, b1, g2, b2,
                 ck_l, cv_l) = per
                h = layer_norm(x, g1, b1)
                qkv = jnp.einsum("bsc,thdc->bsthd", h, qw) + qb
                qh = qkv[:, :, 0].swapaxes(1, 2)     # (B, H, S, Dh)
                kh = qkv[:, :, 1].swapaxes(1, 2)
                vh = qkv[:, :, 2].swapaxes(1, 2)
                ck_l = lax.dynamic_update_slice(
                    ck_l, kh.astype(ck_l.dtype), (0, 0, pos, 0))
                cv_l = lax.dynamic_update_slice(
                    cv_l, vh.astype(cv_l.dtype), (0, 0, pos, 0))
                scores = jnp.einsum("bhsd,bhwd->bhsw", qh, ck_l) \
                    * (Dh ** -0.5)
                mask = jnp.arange(W)[None, :] <= \
                    pos + jnp.arange(S)[:, None]              # (S, W)
                scores = jnp.where(mask[None, None], scores, -1e30)
                p = jax.nn.softmax(scores, axis=-1)
                attn = jnp.einsum("bhsw,bhwd->bhsd", p, cv_l)
                attn = jnp.einsum("bhsd,chd->bsc", attn, pw) + pb
                x = x + attn
                h = layer_norm(x, g2, b2)
                h = h @ f1w.T + f1b
                h = jax.nn.gelu(h) if act == "gelu" \
                    else jnp.maximum(h, 0)
                x = x + (h @ f2w.T + f2b)
                return x, (ck_l, cv_l)

            per_layer = (qkvw, qkvb, pwh, pb, f1w, f1b, f2w, f2b,
                         s["ln1_stack_gamma"], s["ln1_stack_beta"],
                         s["ln2_stack_gamma"], s["ln2_stack_beta"],
                         ck, cv)
            x, (ck2, cv2) = lax.scan(layer, x, per_layer)
            h = layer_norm(x, lnf_g, lnf_b)
            logits = h @ tok_e.T   # bf16 table promotes to f32 in-op
            return ck2, cv2, logits

        self._step_fn = jax.jit(step, donate_argnums=(0, 1))

    def decode(self, ids, max_new_tokens=16, temperature=None,
               rng=None, return_logits=False):
        """ids: (B, T0) NDArray seed; returns (B, T0+N) NDArray like
        generate(), at O(W) per new token.  The cache window is fixed:
        T0 + max_new_tokens must fit max_length (generate()'s sliding
        window has no cache to shift, so it has no such bound).

        With ``return_logits=True`` also returns the (N, B, vocab)
        pre-sampling logits stack (scoring / equivalence checks)."""
        import numpy as np

        import jax.numpy as jnp

        from ... import ndarray as nd

        if self._step_fn is None:
            self._build()
        out = ids.asnumpy().astype(np.int32)
        B, T0 = out.shape
        if T0 + max_new_tokens > self._W:
            raise ValueError(
                f"decode: {T0} seed + {max_new_tokens} new tokens "
                f"exceed the cache window max_length={self._W}; use "
                "generate() for sliding-window decoding")
        ck, cv = self._init_cache(B)
        # Chunked prefill: the whole seed in ONE block-step call.  The
        # seed is right-padded to a power-of-two bucket so a serving
        # loop with varied prompt lengths compiles log2(W) prefill
        # programs, not one per distinct T0.  Pad garbage written at
        # cache positions >= T0 is harmless: position q only becomes
        # attendable at the step whose pos == q, and that same step
        # overwrites q before attending.
        T0p = 8
        while T0p < T0:
            T0p *= 2
        T0p = min(T0p, self._W)
        padded = np.zeros((B, T0p), np.int32)
        padded[:, :T0] = out
        ck, cv, logits = self._step_fn(
            ck, cv, jnp.asarray(0), jnp.asarray(padded))
        logits = logits[:, T0 - 1]
        lg = []
        for n in range(max_new_tokens):
            cur = np.asarray(logits)
            lg.append(cur)
            nxt = _sample(cur, temperature, rng)
            out = np.concatenate([out, nxt[:, None]], axis=1)
            if n < max_new_tokens - 1:   # last token needs no step
                ck, cv, logits = self._step_fn(
                    ck, cv, jnp.asarray(T0 + n), jnp.asarray(nxt[:, None]))
                logits = logits[:, -1]
        toks = nd.array(out.astype(np.float32))
        if return_logits:
            vocab = self._tok.shape[0]
            stacked = np.stack(lg) if lg else \
                np.zeros((0, B, vocab), np.float32)
            return toks, stacked
        return toks


def speculative_decode(target, draft, ids, max_new_tokens=16, k=4,
                       return_stats=False):
    """Greedy speculative decoding (LOSSLESS: emits exactly the tokens
    ``CachedDecoder(target).decode`` would emit greedily).

    The cheap ``draft`` model proposes ``k`` tokens with k O(1)-context
    steps; the ``target`` verifies the whole block in ONE block-step
    (the same MXU-friendly shape as chunked prefill), accepting the
    longest prefix where the target's own greedy choice agrees, plus
    the target's replacement token at the first disagreement.  Batched:
    rows advance in lockstep at the minimum per-row acceptance (greedy
    determinism makes re-proposal of the tail exact, so uniform
    progress stays lossless).

    target/draft: GPTModel or CachedDecoder (tp/bf16 decoders work).
    Returns (B, T0+N) tokens; with ``return_stats=True`` also a dict
    with rounds / accepted-token counts.

    Caveat: "exactly" is up to float32 rounding ties — the S=k+1
    verify step may reduce in a different order than decode()'s S=1
    step, so an argmax sitting inside rounding noise can flip (the
    same class of tie the tp all-reduce path documents).
    """
    import numpy as np

    import jax.numpy as jnp

    from ... import ndarray as nd

    tgt = target if isinstance(target, CachedDecoder) \
        else CachedDecoder(target)
    drf = draft if isinstance(draft, CachedDecoder) \
        else CachedDecoder(draft)
    for dec in (tgt, drf):
        if dec._step_fn is None:
            dec._build()

    out = ids.asnumpy().astype(np.int32)
    B, T0 = out.shape
    total = T0 + max_new_tokens
    if total + k > min(tgt._W, drf._W):
        raise ValueError(
            f"speculative_decode: {total} tokens + {k} draft overshoot "
            f"exceed cache window (target {tgt._W}, draft {drf._W})")

    t_ck, t_cv = tgt._init_cache(B)
    d_ck, d_cv = drf._init_cache(B)
    # prefill BOTH through the seed minus its last token: the invariant
    # is "cache holds positions < P-1; the last committed token is the
    # next thing fed", so the seed's last token heads the first block.
    # Right-padded to a power-of-two bucket (same compile-count and
    # pad-garbage-overwrite argument as decode()'s chunked prefill).
    if T0 > 1:
        Tp = 8
        while Tp < T0 - 1:
            Tp *= 2
        Tp = min(Tp, min(tgt._W, drf._W))
        padded = np.zeros((B, Tp), np.int32)
        padded[:, :T0 - 1] = out[:, :-1]
        t_ck, t_cv, _ = tgt._step_fn(
            t_ck, t_cv, jnp.asarray(0), jnp.asarray(padded))
        d_ck, d_cv, _ = drf._step_fn(
            d_ck, d_cv, jnp.asarray(0), jnp.asarray(padded))

    P = T0
    dp = T0 - 1  # next draft-cache position to write
    rounds = accepted_total = 0
    while P < total:
        # 0. draft cache catch-up: after a full-accept round the bonus
        # token advanced P past what the proposal loop wrote (it writes
        # through P+k-2, the bonus needs P+k-1) — feed the missing
        # committed token(s) so the draft never attends a stale slot
        while dp < P - 1:
            d_ck, d_cv, _ = drf._step_fn(
                d_ck, d_cv, jnp.asarray(dp),
                jnp.asarray(out[:, dp][:, None]))
            dp += 1
        # 1. draft proposes k tokens, one cheap step each
        props = np.zeros((B, k), np.int32)
        last = out[:, P - 1]
        for j in range(k):
            d_ck, d_cv, d_lg = drf._step_fn(
                d_ck, d_cv, jnp.asarray(P - 1 + j),
                jnp.asarray(last[:, None]))
            last = np.argmax(np.asarray(d_lg[:, -1]), axis=-1) \
                .astype(np.int32)
            props[:, j] = last
        dp = P - 1 + k
        # 2. target verifies in ONE (k+1)-block step: inputs are the
        # last committed token + all k proposals at positions P-1..;
        # choice[:, j] is the target's greedy pick for position P+j —
        # including the FREE bonus token choice[:, k] on full accept
        block = np.concatenate([out[:, P - 1:P], props], axis=1)
        t_ck, t_cv, t_lg = tgt._step_fn(
            t_ck, t_cv, jnp.asarray(P - 1), jnp.asarray(block))
        choice = np.argmax(np.asarray(t_lg), axis=-1) \
            .astype(np.int32)                            # (B, k+1)
        # 3. longest agreeing prefix, uniform across the batch
        agree = (props == choice[:, :k])
        full = agree.all(axis=1)
        first_bad = np.where(full, k, np.argmin(agree, axis=1))
        m = int(first_bad.min())
        # commit m accepted proposals + the target's own next token
        # (replacement at the first disagreement, bonus on full accept)
        commit = np.concatenate(
            [props[:, :m], choice[:, m:m + 1]], axis=1)
        commit = commit[:, :total - P]
        out = np.concatenate([out, commit], axis=1)
        P += commit.shape[1]
        rounds += 1
        accepted_total += min(m, commit.shape[1])
    toks = nd.array(out.astype(np.float32))
    if return_stats:
        return toks, {"rounds": rounds, "proposed_per_round": k,
                      "accepted_draft_tokens": accepted_total,
                      "new_tokens": max_new_tokens}
    return toks


# -- pipeline-parallel parts ---------------------------------------------------

class GPTEmbedding(HybridBlock):
    """Token + position embedding front (pipeline prologue)."""

    def __init__(self, vocab_size, units, max_length, dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._vocab = vocab_size
        self._dropout = dropout
        with self.name_scope():
            self.tok_embed_weight = self.params.get(
                "tok_embed_weight", shape=(vocab_size, units))
            self.pos_embed_weight = self.params.get(
                "pos_embed_weight", shape=(max_length, units))
            if dropout:
                self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, ids, tok_embed_weight,
                       pos_embed_weight):
        x = F.Embedding(ids, tok_embed_weight, input_dim=self._vocab,
                        output_dim=self._units)
        x = x + F.slice_axis(pos_embed_weight, axis=0, begin=0,
                             end=ids.shape[1])
        if self._dropout:
            x = self.drop(x)
        return x


class GPTHead(HybridBlock):
    """Final LN + LM projection (pipeline epilogue).  UNTIED: the
    pipeline partitions prologue and epilogue parameters separately, so
    the single-model weight tying cannot span them."""

    def __init__(self, vocab_size, units, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln_f = nn.LayerNorm(in_channels=units)
            self.proj = nn.Dense(vocab_size, in_units=units,
                                 flatten=False)

    def hybrid_forward(self, F, x):
        return self.proj(self.ln_f(x))


def gpt_pipeline_parts(vocab_size=50257, units=768, num_layers=12,
                       num_heads=12, hidden_size=None, max_length=1024,
                       dropout=0.0, attention_impl="dense"):
    """(prologue, trunk stages, epilogue) for parallel.PipelineTrainer:
    a full causal LM as embedding + homogeneous causal layers + head
    (mirrors bert_pipeline_parts for the decoder-only family)."""
    from .bert import TransformerEncoderLayer

    embed = GPTEmbedding(vocab_size, units, max_length, dropout,
                         prefix="ppgptembed_")
    layers = [TransformerEncoderLayer(
        units, num_heads, hidden_size or 4 * units, dropout,
        attention_impl, causal=True, prefix=f"ppgptlayer{i}_")
        for i in range(num_layers)]
    head = GPTHead(vocab_size, units, prefix="ppgpthead_")
    return embed, layers, head


def beam_generate(model, ids, max_new_tokens=16, beam_size=4,
                  eos_id=None, alpha=0.6):
    """Beam-search continuation of a shared prompt (decoder-only analog
    of transformer.beam_search, same ``beam_loop`` core and GNMT length
    penalty).  ids: (B, T0) NDArray seed; returns
    (tokens (B, T0+N), scores (B,))."""
    import numpy as np

    from ... import autograd
    from ... import ndarray as nd
    from .transformer import beam_loop

    seed = ids.asnumpy().astype(np.int32)
    B = seed.shape[0]

    def score_last(flat):
        with autograd.predict_mode():
            return _windowed_last_logits(model, flat, nd, np)

    out, scores = beam_loop(score_last, B, beam_size, None, eos_id,
                            max_new_tokens, alpha, seed_beams=seed)
    return nd.array(out.astype(np.float32)), scores
