"""Decoder-only (GPT-style) language model family.

Beyond-reference breadth: the 2018-era reference zoo has no decoder-only
LM (its nearest is example/rnn word_lm and the NMT Transformer decoder);
this family completes the transformer spread — encoder (BERT),
encoder-decoder (transformer.py NMT), decoder-only (here) — on the same
TPU-first trunk primitives:

- causal attention via the SAME packed-qkv MHA op (flash/ring/ulysses
  ``attention_impl`` all apply — the long-context causal config);
- ``scan_layers=True`` compiles the trunk as one scanned layer
  (compile-time scalability, same as BERT's bench config);
- the LM head is WEIGHT-TIED to the token embedding (standard GPT-2
  parameterization): one (vocab, units) matrix serves both.
"""

from __future__ import annotations

from ..block import HybridBlock
from .. import nn
from .bert import ScanTransformerEncoder, TransformerEncoder


class GPTModel(HybridBlock):
    """Token+position embedding → causal pre-LN trunk → tied-head
    logits.  Input: (B, T) int token ids; output: (B, T, vocab)."""

    def __init__(self, vocab_size=50257, units=768, num_layers=12,
                 num_heads=12, max_length=1024, hidden_size=None,
                 dropout=0.1, attention_impl="dense", scan_layers=False,
                 remat=False, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._vocab = vocab_size
        self._max_length = max_length
        self._dropout = dropout
        with self.name_scope():
            self.tok_embed_weight = self.params.get(
                "tok_embed_weight", shape=(vocab_size, units))
            self.pos_embed_weight = self.params.get(
                "pos_embed_weight", shape=(max_length, units))
            if scan_layers:
                self.encoder = ScanTransformerEncoder(
                    num_layers, units, num_heads, hidden_size, dropout,
                    attention_impl, causal=True, remat=remat,
                    prefix="trunk_")
            else:
                self.encoder = TransformerEncoder(
                    num_layers, units, num_heads, hidden_size, dropout,
                    attention_impl, causal=True, prefix="trunk_")
            if dropout:
                self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, ids, tok_embed_weight,
                       pos_embed_weight):
        # stored sizes keep the op attrs static ints under the trace
        x = F.Embedding(ids, tok_embed_weight, input_dim=self._vocab,
                        output_dim=self._units)
        T = ids.shape[1]
        x = x + F.slice_axis(pos_embed_weight, axis=0, begin=0, end=T)
        if self._dropout:
            x = self.drop(x)
        h = self.encoder(x)                       # (B, T, C)
        # tied head: logits = h @ embedᵀ — one big MXU matmul
        # (kwarg shape= so the symbolic trace maps it as an attribute)
        flat = F.reshape(h, shape=(-1, self._units))
        logits = F.dot(flat, tok_embed_weight, transpose_b=True)
        return F.reshape(logits, shape=(-1, T, self._vocab))


def _lm_loss_pure(logits, labels):
    """Shifted next-token cross-entropy; labels < 0 are ignored —
    the shift plus the zoo's shared masked-CE."""
    from .bert import masked_token_ce

    return masked_token_ce(logits[:, :-1], labels[:, 1:])


class GPTLMLoss(HybridBlock):
    """Causal LM loss: mean next-token NLL over valid (>= 0) labels.
    Call with (logits, token_ids) — the shift happens inside."""

    def hybrid_forward(self, F, logits, labels):
        from ...ndarray.register import invoke_simple

        return invoke_simple(_lm_loss_pure, (logits, labels))


def _windowed_last_logits(model, flat, nd_mod, np_mod):
    """Last-position logits for (N, T) token rows through the model's
    fixed max_length window: right-pad to W (one compiled shape — causal
    masking hides the pad) and read position cur-1.  Shared by
    generate() and beam_generate()."""
    W = model._max_length
    ctx = flat[:, -W:]
    cur = ctx.shape[1]
    if cur < W:
        ctx = np_mod.concatenate(
            [ctx, np_mod.zeros((ctx.shape[0], W - cur), np_mod.int32)],
            axis=1)
    logits = model(nd_mod.array(ctx.astype(np_mod.float32))).asnumpy()
    return logits[:, cur - 1]


def _sample(last, temperature, rng):
    """Pick next tokens from (B, vocab) logits: greedy, or softmax
    sampling at the given temperature (one home for both decode paths)."""
    import numpy as np

    if temperature:
        z = last / temperature
        z = z - z.max(axis=-1, keepdims=True)
        p = np.exp(z) / np.exp(z).sum(axis=-1, keepdims=True)
        rng = rng or np.random.default_rng()
        return np.stack([rng.choice(p.shape[-1], p=row)
                         for row in p]).astype(np.int32)
    return last.argmax(axis=-1).astype(np.int32)


def generate(model, ids, max_new_tokens=16, temperature=None, rng=None):
    """Greedy (or sampled) decode by full-recompute per step — the
    simple deploy path; ids: (B, T0) NDArray of seed tokens.

    The context is RIGHT-padded to max_length so every step runs at ONE
    shape (one compile, critical on the slow-AOT TPU tunnel); causal
    masking makes positions > cur-1 invisible to the read position, so
    the pad content never matters."""
    import numpy as np

    from ... import ndarray as nd

    out = ids.asnumpy().astype(np.int32)
    for _ in range(max_new_tokens):
        last = _windowed_last_logits(model, out, nd, np)
        nxt = _sample(last, temperature, rng)
        out = np.concatenate([out, nxt[:, None]], axis=1)
    return nd.array(out.astype(np.float32))


def gpt2_small(**kwargs):
    """GPT-2 124M config."""
    kwargs.setdefault("vocab_size", 50257)
    kwargs.setdefault("units", 768)
    kwargs.setdefault("num_layers", 12)
    kwargs.setdefault("num_heads", 12)
    kwargs.setdefault("max_length", 1024)
    return GPTModel(**kwargs)


def gpt2_medium(**kwargs):
    kwargs.setdefault("vocab_size", 50257)
    kwargs.setdefault("units", 1024)
    kwargs.setdefault("num_layers", 24)
    kwargs.setdefault("num_heads", 16)
    kwargs.setdefault("max_length", 1024)
    return GPTModel(**kwargs)


def gpt_tiny(**kwargs):
    """Test-sized config."""
    kwargs.setdefault("vocab_size", 128)
    kwargs.setdefault("units", 32)
    kwargs.setdefault("num_layers", 2)
    kwargs.setdefault("num_heads", 2)
    kwargs.setdefault("max_length", 64)
    kwargs.setdefault("dropout", 0.0)
    return GPTModel(**kwargs)


# -- KV-cache incremental decoding ---------------------------------------------
#
# TPU-native inference engine for the decoder-only family: a STATIC
# (L, B, H, W, Dh) key/value cache updated with dynamic_update_slice at
# a traced position, so the per-token step is ONE compiled program doing
# O(W) attention instead of recomputing the O(W²) trunk (the role the
# reference's inference-time BucketingModule/exec cache plays for RNNs).


class CachedDecoder:
    """Wraps a GPTModel into jitted prefill/step functions.

    Works for scan and unstacked trunks alike: parameters are pulled
    into (L, ...) stacks once at construction.  ``decode`` mirrors
    ``generate``'s sampling surface but runs the cached path.

    Pass ``mesh=`` (with a ``tp_axis`` mesh axis) for tensor-parallel
    serving: heads, the KV cache, and the FFN hidden dim shard over the
    axis (Megatron column/row rules) and GSPMD inserts the two
    per-layer all-reduces — multi-chip decode with no code change.
    """

    def __init__(self, model, mesh=None, tp_axis="tp"):
        self._W = model._max_length
        self._mesh = mesh
        self._tp_axis = tp_axis
        params = dict(model.collect_params())

        def get1(suffix):
            ks = [k for k in params if k.endswith(suffix)]
            assert len(ks) == 1, (suffix, ks)
            return params[ks[0]].data()._data

        if any(k.endswith("qkv_stack_weight") for k in params):
            stacks = {nm: get1(nm) for nm in (
                "qkv_stack_weight", "qkv_stack_bias",
                "proj_stack_weight", "proj_stack_bias",
                "ffn1_stack_weight", "ffn1_stack_bias",
                "ffn2_stack_weight", "ffn2_stack_bias",
                "ln1_stack_gamma", "ln1_stack_beta",
                "ln2_stack_gamma", "ln2_stack_beta")}
            lnf_g, lnf_b = get1("lnf_gamma"), get1("lnf_beta")
            num_heads = model.encoder._num_heads
            act = model.encoder._activation
        else:
            enc = model.encoder
            layers = list(enc.layers._children.values())
            num_heads = layers[0]._num_heads
            act = layers[0]._activation

            def stacked(name):
                import jax.numpy as jnp

                return jnp.stack([
                    getattr(l, name).data()._data for l in layers])

            stacks = {
                "qkv_stack_weight": stacked("qkv_weight"),
                "qkv_stack_bias": stacked("qkv_bias"),
                "proj_stack_weight": stacked("proj_weight"),
                "proj_stack_bias": stacked("proj_bias"),
                "ffn1_stack_weight": stacked("ffn1_weight"),
                "ffn1_stack_bias": stacked("ffn1_bias"),
                "ffn2_stack_weight": stacked("ffn2_weight"),
                "ffn2_stack_bias": stacked("ffn2_bias"),
            }
            import jax.numpy as jnp

            stacks["ln1_stack_gamma"] = jnp.stack(
                [l.ln1.gamma.data()._data for l in layers])
            stacks["ln1_stack_beta"] = jnp.stack(
                [l.ln1.beta.data()._data for l in layers])
            stacks["ln2_stack_gamma"] = jnp.stack(
                [l.ln2.gamma.data()._data for l in layers])
            stacks["ln2_stack_beta"] = jnp.stack(
                [l.ln2.beta.data()._data for l in layers])
            lnf_g = enc.ln_f.gamma.data()._data
            lnf_b = enc.ln_f.beta.data()._data

        self._stacks = stacks
        self._lnf = (lnf_g, lnf_b)
        self._tok = get1("tok_embed_weight")
        self._pos = get1("pos_embed_weight")
        self._H = num_heads
        self._act = act
        self._step_fn = None

    def _shard(self, arr, spec):
        """Place with a NamedSharding when a tp mesh is set (GSPMD then
        propagates the layout and inserts the collectives); no-op on the
        single-device path."""
        if self._mesh is None:
            return arr
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.device_put(arr, NamedSharding(self._mesh, P(*spec)))

    def _build(self):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ...ops.nn import layer_norm

        H, W = self._H, self._W
        tok_e, pos_e = self._tok, self._pos
        lnf_g, lnf_b = self._lnf
        s = self._stacks
        C = tok_e.shape[1]
        Dh = C // H
        act = self._act
        L = s["qkv_stack_weight"].shape[0]
        F = s["ffn1_stack_weight"].shape[1]
        tp = self._tp_axis
        if self._mesh is not None:
            n_tp = self._mesh.shape[tp]
            if H % n_tp or F % n_tp:
                raise ValueError(
                    f"CachedDecoder: tp axis size {n_tp} must divide "
                    f"both num_heads={H} and ffn hidden={F}")

        # Head-/hidden-major restructuring so a tp mesh shards the H and
        # F dims (Megatron rules: column-parallel qkv/ffn1, row-parallel
        # proj/ffn2 — the contraction over a sharded dim becomes XLA's
        # all-reduce).  Single-device runs the same code unsharded.
        qkvw = self._shard(
            s["qkv_stack_weight"].reshape(L, 3, H, Dh, C),
            (None, None, tp))
        qkvb = self._shard(s["qkv_stack_bias"].reshape(L, 3, H, Dh),
                           (None, None, tp))
        pwh = self._shard(s["proj_stack_weight"].reshape(L, C, H, Dh),
                          (None, None, tp))
        f1w = self._shard(s["ffn1_stack_weight"], (None, tp))
        f1b = self._shard(s["ffn1_stack_bias"], (None, tp))
        f2w = self._shard(s["ffn2_stack_weight"], (None, None, tp))
        pb, f2b = s["proj_stack_bias"], s["ffn2_stack_bias"]

        def step(ck, cv, pos, tok):
            """ck/cv: (L, B, H, W, Dh); pos: scalar; tok: (B,) int32.
            Returns (new_ck, new_cv, logits (B, vocab))."""
            x = jnp.take(tok_e, tok, axis=0) + pos_e[pos]     # (B, C)

            def layer(x, per):
                (qw, qb, pw, pb, f1w, f1b, f2w, f2b, g1, b1, g2, b2,
                 ck_l, cv_l) = per
                h = layer_norm(x, g1, b1)
                qkv = jnp.einsum("bc,thdc->bthd", h, qw) + qb  # (B,3,H,Dh)
                qh, kh, vh = qkv[:, 0], qkv[:, 1], qkv[:, 2]
                ck_l = lax.dynamic_update_slice(
                    ck_l, kh[:, :, None], (0, 0, pos, 0))
                cv_l = lax.dynamic_update_slice(
                    cv_l, vh[:, :, None], (0, 0, pos, 0))
                scores = jnp.einsum("bhd,bhwd->bhw", qh, ck_l) \
                    * (Dh ** -0.5)
                mask = jnp.arange(W) <= pos
                scores = jnp.where(mask[None, None], scores, -1e30)
                p = jax.nn.softmax(scores, axis=-1)
                attn = jnp.einsum("bhw,bhwd->bhd", p, cv_l)
                attn = jnp.einsum("bhd,chd->bc", attn, pw) + pb
                x = x + attn
                h = layer_norm(x, g2, b2)
                h = h @ f1w.T + f1b
                h = jax.nn.gelu(h) if act == "gelu" \
                    else jnp.maximum(h, 0)
                x = x + (h @ f2w.T + f2b)
                return x, (ck_l, cv_l)

            per_layer = (qkvw, qkvb, pwh, pb, f1w, f1b, f2w, f2b,
                         s["ln1_stack_gamma"], s["ln1_stack_beta"],
                         s["ln2_stack_gamma"], s["ln2_stack_beta"],
                         ck, cv)
            x, (ck2, cv2) = lax.scan(layer, x, per_layer)
            h = layer_norm(x, lnf_g, lnf_b)
            logits = h @ tok_e.T
            return ck2, cv2, logits

        self._step_fn = jax.jit(step, donate_argnums=(0, 1))

    def decode(self, ids, max_new_tokens=16, temperature=None,
               rng=None, return_logits=False):
        """ids: (B, T0) NDArray seed; returns (B, T0+N) NDArray like
        generate(), at O(W) per new token.  The cache window is fixed:
        T0 + max_new_tokens must fit max_length (generate()'s sliding
        window has no cache to shift, so it has no such bound).

        With ``return_logits=True`` also returns the (N, B, vocab)
        pre-sampling logits stack (scoring / equivalence checks)."""
        import numpy as np

        import jax.numpy as jnp

        from ... import ndarray as nd

        if self._step_fn is None:
            self._build()
        out = ids.asnumpy().astype(np.int32)
        B, T0 = out.shape
        L = self._stacks["qkv_stack_weight"].shape[0]
        H, W = self._H, self._W
        C = self._tok.shape[1]
        Dh = C // H
        if T0 + max_new_tokens > W:
            raise ValueError(
                f"decode: {T0} seed + {max_new_tokens} new tokens "
                f"exceed the cache window max_length={W}; use "
                "generate() for sliding-window decoding")
        cache_spec = (None, None, self._tp_axis, None, None)
        ck = self._shard(jnp.zeros((L, B, H, W, Dh), self._tok.dtype),
                         cache_spec)
        cv = self._shard(jnp.zeros((L, B, H, W, Dh), self._tok.dtype),
                         cache_spec)
        # prefill: feed seed tokens one by one through the SAME step fn
        # (one compiled program total; prefill cost O(T0·W))
        logits = None
        for t in range(T0):
            ck, cv, logits = self._step_fn(
                ck, cv, jnp.asarray(t), jnp.asarray(out[:, t]))
        lg = []
        for n in range(max_new_tokens):
            cur = np.asarray(logits)
            lg.append(cur)
            nxt = _sample(cur, temperature, rng)
            out = np.concatenate([out, nxt[:, None]], axis=1)
            if n < max_new_tokens - 1:   # last token needs no step
                ck, cv, logits = self._step_fn(
                    ck, cv, jnp.asarray(T0 + n), jnp.asarray(nxt))
        toks = nd.array(out.astype(np.float32))
        if return_logits:
            vocab = self._tok.shape[0]
            stacked = np.stack(lg) if lg else \
                np.zeros((0, B, vocab), np.float32)
            return toks, stacked
        return toks


# -- pipeline-parallel parts ---------------------------------------------------

class GPTEmbedding(HybridBlock):
    """Token + position embedding front (pipeline prologue)."""

    def __init__(self, vocab_size, units, max_length, dropout=0.0,
                 **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._vocab = vocab_size
        self._dropout = dropout
        with self.name_scope():
            self.tok_embed_weight = self.params.get(
                "tok_embed_weight", shape=(vocab_size, units))
            self.pos_embed_weight = self.params.get(
                "pos_embed_weight", shape=(max_length, units))
            if dropout:
                self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, ids, tok_embed_weight,
                       pos_embed_weight):
        x = F.Embedding(ids, tok_embed_weight, input_dim=self._vocab,
                        output_dim=self._units)
        x = x + F.slice_axis(pos_embed_weight, axis=0, begin=0,
                             end=ids.shape[1])
        if self._dropout:
            x = self.drop(x)
        return x


class GPTHead(HybridBlock):
    """Final LN + LM projection (pipeline epilogue).  UNTIED: the
    pipeline partitions prologue and epilogue parameters separately, so
    the single-model weight tying cannot span them."""

    def __init__(self, vocab_size, units, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.ln_f = nn.LayerNorm(in_channels=units)
            self.proj = nn.Dense(vocab_size, in_units=units,
                                 flatten=False)

    def hybrid_forward(self, F, x):
        return self.proj(self.ln_f(x))


def gpt_pipeline_parts(vocab_size=50257, units=768, num_layers=12,
                       num_heads=12, hidden_size=None, max_length=1024,
                       dropout=0.0, attention_impl="dense"):
    """(prologue, trunk stages, epilogue) for parallel.PipelineTrainer:
    a full causal LM as embedding + homogeneous causal layers + head
    (mirrors bert_pipeline_parts for the decoder-only family)."""
    from .bert import TransformerEncoderLayer

    embed = GPTEmbedding(vocab_size, units, max_length, dropout,
                         prefix="ppgptembed_")
    layers = [TransformerEncoderLayer(
        units, num_heads, hidden_size or 4 * units, dropout,
        attention_impl, causal=True, prefix=f"ppgptlayer{i}_")
        for i in range(num_layers)]
    head = GPTHead(vocab_size, units, prefix="ppgpthead_")
    return embed, layers, head


def beam_generate(model, ids, max_new_tokens=16, beam_size=4,
                  eos_id=None, alpha=0.6):
    """Beam-search continuation of a shared prompt (decoder-only analog
    of transformer.beam_search, same ``beam_loop`` core and GNMT length
    penalty).  ids: (B, T0) NDArray seed; returns
    (tokens (B, T0+N), scores (B,))."""
    import numpy as np

    from ... import autograd
    from ... import ndarray as nd
    from .transformer import beam_loop

    seed = ids.asnumpy().astype(np.int32)
    B = seed.shape[0]

    def score_last(flat):
        with autograd.predict_mode():
            return _windowed_last_logits(model, flat, nd, np)

    out, scores = beam_loop(score_last, B, beam_size, None, eos_id,
                            max_new_tokens, alpha, seed_beams=seed)
    return nd.array(out.astype(np.float32)), scores
