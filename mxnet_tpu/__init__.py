"""mxnet_tpu — a TPU-native deep learning framework with the MXNet API surface.

A from-scratch rebuild of the capabilities of apache/incubator-mxnet
(reference: Mooonside/incubator-mxnet) designed TPU-first on jax/XLA/Pallas:

- ``NDArray`` keeps MXNet's asynchronous, mutable array semantics
  (reference: include/mxnet/ndarray.h, src/ndarray/ndarray.cc) but is backed
  by immutable ``jax.Array`` buffers — mutation is handle-swapping with a
  version counter; "async engine" scheduling (reference: src/engine/) is
  delegated to XLA/PJRT's already-asynchronous dispatch, with
  ``wait_to_read()`` mapping to ``block_until_ready()``.
- The operator library (reference: src/operator/) is a registry of pure JAX
  functions; the ``mx.nd.*`` / ``mx.np``-style wrappers are generated from the
  registry at import time, mirroring python/mxnet/ndarray/register.py.
- ``gluon`` keeps Block/HybridBlock/Parameter/Trainer semantics; ``hybridize()``
  compiles the whole step with ``jax.jit`` (the CachedOp analog,
  reference: src/imperative/cached_op.cc).
- ``kvstore`` maps push/pull onto XLA collectives over the ICI mesh
  (reference: src/kvstore/).
- ``parallel`` is new, TPU-first: device meshes, data/tensor/pipeline/sequence
  parallelism via jax.sharding + shard_map, ring attention over ppermute.
"""

__version__ = "0.1.0"

# memory-pool env knobs must hit the XLA client env BEFORE jax loads
# (reference analog: pool env read at Storage::Get())
from . import storage
storage.apply_env()

from . import base
from .base import MXNetError
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import engine
from . import ops
from . import ndarray
from . import ndarray as nd
from .ndarray import NDArray
from . import autograd
from . import random
from .random import seed

# MXNet-compatible top-level saves (mx.nd.save / mx.nd.load are the canonical
# entry points; these mirror python/mxnet/ndarray/utils.py).
from .ndarray import save, load

# Frontend layers: imported when present (they land milestone by milestone;
# once the build is complete these are all unconditional).
import importlib as _importlib

for _mod in ("initializer", "optimizer", "metric", "callback", "kvstore",
             "gluon", "io", "recordio", "image", "profiler", "runtime",
             "parallel", "test_utils", "util", "visualization", "operator",
             "symbol", "model", "module", "lr_scheduler", "distributed",
             "amp", "checkpoint", "contrib", "rtc", "image_detection",
             "subgraph", "attribute", "monitor", "resilience", "numerics",
             "telemetry", "serving", "autotune", "embedding"):
    try:
        globals()[_mod] = _importlib.import_module(f".{_mod}", __name__)
    except ModuleNotFoundError as _e:
        # only tolerate the module itself not existing yet, not its bugs
        if _e.name != f"{__name__}.{_mod}":
            raise
del _importlib, _mod

if "kvstore" in globals():
    kv = globals()["kvstore"]
    KVStore = kv.KVStore
if "initializer" in globals():
    init = globals()["initializer"]
if "optimizer" in globals():
    lr_scheduler = optimizer.lr_scheduler
if "symbol" in globals():
    sym = globals()["symbol"]
if "module" in globals():
    mod = globals()["module"]
if "visualization" in globals():
    viz = globals()["visualization"]
if "attribute" in globals():
    AttrScope = attribute.AttrScope
if "monitor" in globals():
    mon = globals()["monitor"]  # reference alias: mx.mon.Monitor
