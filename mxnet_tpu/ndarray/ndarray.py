"""The NDArray: MXNet's array semantics on immutable XLA buffers.

Reference parity: include/mxnet/ndarray.h + src/ndarray/ndarray.cc +
python/mxnet/ndarray/ndarray.py.

Design notes (TPU-first):
- The underlying ``jax.Array`` is immutable; MXNet's in-place mutation
  (``x += 1``, ``x[2:5] = v``, optimizer updates) becomes handle swapping —
  ``self._data`` is replaced and ``self._version`` bumped.  This preserves the
  reference's aliasing-visible semantics at the Python level while every
  actual buffer stays functional for XLA (and the autograd tape can never be
  corrupted by mutation, unlike the reference which must version-check).
- Asynchrony comes from PJRT: ops return immediately with futures;
  ``wait_to_read()`` = ``block_until_ready()``; device errors surface at the
  sync point, matching the reference's deferred-exception semantics
  (src/engine/threaded_engine.cc exception propagation).
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError, np_dtype
from ..context import Context, current_context
from .. import engine


def _is_jax_array(x):
    import jax

    return isinstance(x, jax.Array) or hasattr(x, "aval")


class NDArray:
    __slots__ = ("_data", "_ctx", "_version", "_grad", "_grad_req",
                 "_tape_node", "_stype", "__weakref__")

    # make NumPy defer to NDArray dunders (mx.nd semantics)
    __array_priority__ = 100.0

    def __init__(self, data, ctx=None, stype="default"):
        self._data = data
        self._ctx = ctx
        self._version = 0
        self._grad = None
        self._grad_req = "null"
        self._tape_node = None
        self._stype = stype

    # -- basic properties ------------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        dt = self._data.dtype
        return dt.type if hasattr(dt, "type") and dt.type.__module__ == "numpy" else dt

    @property
    def size(self):
        return int(self._data.size)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def stype(self):
        return self._stype

    @property
    def context(self):
        if self._ctx is not None:
            return self._ctx
        try:
            dev = list(self._data.devices())[0]
            plat = dev.platform
        except Exception:
            return current_context()
        if plat == "cpu":
            return Context("cpu", dev.id)
        return Context("tpu", dev.id)

    ctx = context

    @property
    def T(self):
        return self.transpose()

    @property
    def grad(self):
        return self._grad

    @property
    def handle(self):  # reference-compat attribute
        return self._data

    @property
    def version(self):
        return self._version

    def _on_tape(self):
        return self._tape_node is not None or self._grad_req != "null"

    # -- sync / host transfer --------------------------------------------------
    def wait_to_read(self):
        self._data.block_until_ready()
        return self

    wait_to_write = wait_to_read

    def asnumpy(self):
        return _np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def tolist(self):
        return self.asnumpy().tolist()

    # -- device movement -------------------------------------------------------
    def as_in_context(self, ctx):
        import jax

        if ctx == self.context:
            return self
        out = jax.device_put(self._data, ctx.jax_device)
        return NDArray(out, ctx)

    as_in_ctx = as_in_context

    def copyto(self, other):
        import jax

        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device),
                           other)
        if isinstance(other, NDArray):
            other._data = jax.device_put(
                self._data.astype(other._data.dtype),
                list(other._data.devices())[0])
            other._version += 1
            return other
        raise MXNetError(f"cannot copyto {type(other)}")

    def copy(self):
        return NDArray(self._data, self._ctx)

    def astype(self, dtype, copy=True):
        from ..base import x64_scope_if

        with x64_scope_if(dtype):
            return self._apply(lambda d: d.astype(np_dtype(dtype)))

    # -- autograd --------------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        import jax.numpy as jnp

        if stype == "row_sparse":
            # compact gradient buffer: O(touched rows) after backward
            from .sparse import zeros as sparse_zeros

            self._grad = sparse_zeros("row_sparse", self.shape,
                                      self._ctx, self._data.dtype)
        else:
            self._grad = NDArray(jnp.zeros_like(self._data), self._ctx)
        self._grad_req = grad_req
        self._tape_node = None

    def detach(self):
        out = NDArray(self._data, self._ctx)
        return out

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else
                          None, retain_graph, train_mode)

    # -- op plumbing -----------------------------------------------------------
    def _apply(self, fn, *others, name=""):
        """Run fn over the raw arrays (self first), with tape recording."""
        from .register import invoke_simple

        return invoke_simple(fn, (self,) + others, name=name)

    # -- mutation (handle-swap) ------------------------------------------------
    def _set_data(self, jarr):
        self._data = engine.maybe_sync(jarr)
        self._version += 1

    def __setitem__(self, key, value):
        from ..base import is_64bit_dtype, x64_scope

        key = _unwrap_index(key)
        if isinstance(value, NDArray):
            value = value._data
        if isinstance(key, tuple) and len(key) == 0:
            key = Ellipsis
        # x64 when the index space, the array's own dtype, or the
        # assigned scalar needs 64 bits: under x32 a scatter on a >2^31
        # dim silently DROPS updates, an index past 2^31 can't be
        # carried, and an int64 value wraps through canonicalization
        big_val = isinstance(value, int) and abs(value) > _INT32_MAX
        with x64_scope(_index_needs_x64(key, self._data.shape)
                       or is_64bit_dtype(self._data.dtype) or big_val):
            self._set_data(self._data.at[key].set(value))

    def __getitem__(self, key):
        from ..base import x64_scope

        key2 = _unwrap_index(key)
        # the x64 case still routes through _apply so tape recording,
        # engine sync, and context propagation are identical
        with x64_scope(_index_needs_x64(key2, self._data.shape)):
            return self._apply(lambda d: d[key2], name="getitem")

    # -- python protocol -------------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("The truth value of an NDArray with multiple "
                         "elements is ambiguous.")

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __index__(self):
        return int(self.asscalar())

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __repr__(self):
        arr = self.asnumpy()
        return f"\n{arr}\n<NDArray {'x'.join(map(str, self.shape))} " \
               f"@{self.context}>"

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, **kwargs):
        return self._data.__dlpack__(**kwargs)

    # pickle via host numpy (reference NDArrays pickle through save/load
    # bytes; used by Updater.get_states / DataLoader workers)
    def __getstate__(self):
        return {"data": self.asnumpy(), "stype": self._stype}

    def __setstate__(self, state):
        import jax.numpy as jnp

        self._data = jnp.asarray(state["data"])
        self._ctx = None
        self._version = 0
        self._grad = None
        self._grad_req = "null"
        self._tape_node = None
        self._stype = state.get("stype", "default")

    def __reduce__(self):
        return (_unpickle_ndarray, (self.asnumpy(), self._stype))

    # NDArray equality is elementwise (reference semantics) → unhashable.
    __hash__ = None  # type: ignore

    # -- arithmetic ------------------------------------------------------------
    def _binop(self, other, opname, reverse=False):
        from .register import invoke_registered

        if isinstance(other, _np.ndarray):
            import jax.numpy as jnp

            other = NDArray(jnp.asarray(other))
        a, b = (other, self) if reverse else (self, other)
        return invoke_registered(opname, (a, b), {})

    def __add__(self, o):
        return self._binop(o, "broadcast_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "broadcast_sub")

    def __rsub__(self, o):
        return self._binop(o, "broadcast_sub", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "broadcast_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "broadcast_div")

    def __rtruediv__(self, o):
        return self._binop(o, "broadcast_div", reverse=True)

    def __mod__(self, o):
        return self._binop(o, "broadcast_mod")

    def __rmod__(self, o):
        return self._binop(o, "broadcast_mod", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power")

    def __rpow__(self, o):
        return self._binop(o, "broadcast_power", reverse=True)

    def __matmul__(self, o):
        return self._binop(o, "dot")

    def __neg__(self):
        return self._apply(lambda d: -d, name="negative")

    def __abs__(self):
        return self._apply(lambda d: abs(d), name="abs")

    def __eq__(self, o):
        return self._binop(o, "broadcast_equal")

    def __ne__(self, o):
        return self._binop(o, "broadcast_not_equal")

    def __gt__(self, o):
        return self._binop(o, "broadcast_greater")

    def __ge__(self, o):
        return self._binop(o, "broadcast_greater_equal")

    def __lt__(self, o):
        return self._binop(o, "broadcast_lesser")

    def __le__(self, o):
        return self._binop(o, "broadcast_lesser_equal")

    # in-place: handle swap (see module docstring)
    def _adopt(self, out):
        """Take over `out`'s buffer and tape position (in-place semantics)."""
        self._data = out._data
        self._version += 1
        self._tape_node = out._tape_node
        if self._tape_node is not None:
            outs = self._tape_node.outputs
            for i, o in enumerate(outs):
                if o is out:
                    outs[i] = self  # the node now produces *this* handle
                    break
        return self

    def _ibinop(self, other, opname):
        return self._adopt(self._binop(other, opname))

    def __iadd__(self, o):
        return self._ibinop(o, "broadcast_add")

    def __isub__(self, o):
        return self._ibinop(o, "broadcast_sub")

    def __imul__(self, o):
        return self._ibinop(o, "broadcast_mul")

    def __itruediv__(self, o):
        return self._ibinop(o, "broadcast_div")

    # -- sparse-compat ---------------------------------------------------------
    def tostype(self, stype):
        if stype == "row_sparse":
            from .sparse import row_sparse_array

            return row_sparse_array(self)
        if stype == "csr":
            from .sparse import csr_matrix

            return csr_matrix(self)
        return NDArray(self._data, self._ctx)

    # reshape needs to support reshape(2,3), reshape((2,3)), and special codes
    def reshape(self, *shape, **kwargs):
        from .register import invoke_registered

        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        if not shape and "shape" in kwargs:
            shape = kwargs.pop("shape")
        return invoke_registered("reshape", (self,),
                                 {"shape": shape, **kwargs})

    def reshape_like(self, other):
        from .register import invoke_registered

        return invoke_registered("reshape_like", (self, other), {})


_INT32_MAX = 2 ** 31 - 1


def _index_needs_x64(key, shape=()):
    """True when indexing must run under x64 — any integer index /
    slice bound past int32 range, or ANY dim of the indexed array past
    2^31 (x32 gather/scatter on such arrays silently truncates or
    drops; the INT64_TENSOR_SIZE large-tensor path)."""
    if shape and max(shape) > _INT32_MAX:
        return True

    def big(v):
        return isinstance(v, int) and not isinstance(v, bool) \
            and abs(v) > _INT32_MAX

    items = key if isinstance(key, tuple) else (key,)
    for it in items:
        if big(it):
            return True
        if isinstance(it, slice) and (
                big(it.start) or big(it.stop) or big(it.step)):
            return True
    return False


def _unwrap_index(key):
    if isinstance(key, NDArray):
        import jax.numpy as jnp

        k = key._data
        return k.astype(jnp.int32) if jnp.issubdtype(k.dtype, jnp.floating) \
            else k
    if isinstance(key, tuple):
        return tuple(_unwrap_index(k) for k in key)
    return key


def _from_jax(arr, ctx=None) -> NDArray:
    return NDArray(arr, ctx)


def _unpickle_ndarray(np_data, stype):
    import jax.numpy as jnp

    from ..base import x64_scope_if

    with x64_scope_if(np_data.dtype):
        return NDArray(jnp.asarray(np_data), stype=stype)
