"""mx.nd — the imperative NDArray API.

Reference parity: python/mxnet/ndarray/ — the module namespace carries the
NDArray class, creation functions, and every registered op as a generated
wrapper (codegen analog of register.py's _init_ops).
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError, np_dtype, x64_scope_if
from ..context import Context, current_context
from .ndarray import NDArray, _from_jax
from . import register as _register
from .utils import save, load


def _device(ctx):
    ctx = ctx or current_context()
    return ctx.jax_device, ctx


def array(source_array, ctx=None, dtype=None):
    import jax
    import jax.numpy as jnp

    if isinstance(source_array, NDArray):
        out = source_array.astype(dtype) if dtype else source_array.copy()
        return out.as_in_context(ctx) if ctx else out
    dev, ctx = _device(ctx)
    np_arr = _np.asarray(source_array,
                         dtype=None if dtype in ("bfloat16", None) else dtype)
    if dtype is None and np_arr.dtype != _np.bool_:
        # reference semantics: default dtype is float32 for any non-NDArray
        # source (python/mxnet/ndarray/ndarray.py `array`)
        np_arr = np_arr.astype(_np.float32)
    # explicitly-requested 64-bit dtypes create under x64: jax's x32
    # default would silently truncate (int64 values past 2^31 WRAP)
    with x64_scope_if(dtype):
        arr = jax.device_put(jnp.asarray(np_arr), dev)
    if dtype == "bfloat16":
        arr = arr.astype(jnp.bfloat16)
    return NDArray(arr, ctx)


def zeros(shape, ctx=None, dtype=None, **kwargs):
    import jax
    import jax.numpy as jnp

    dev, ctx = _device(ctx)
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    with x64_scope_if(dtype):
        return NDArray(
            jax.device_put(jnp.zeros(shape, np_dtype(dtype)), dev), ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    import jax
    import jax.numpy as jnp

    dev, ctx = _device(ctx)
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    with x64_scope_if(dtype):
        return NDArray(
            jax.device_put(jnp.ones(shape, np_dtype(dtype)), dev), ctx)


def full(shape, val, ctx=None, dtype=None):
    import jax
    import jax.numpy as jnp

    dev, ctx = _device(ctx)
    shape = (shape,) if isinstance(shape, int) else tuple(shape)
    with x64_scope_if(dtype):
        return NDArray(jax.device_put(
            jnp.full(shape, val, np_dtype(dtype)), dev), ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx, dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    import jax
    import jax.numpy as jnp

    dev, ctx = _device(ctx)
    with x64_scope_if(dtype):
        out = jnp.arange(start, stop, step, np_dtype(dtype or "float32"))
        if repeat > 1:
            out = jnp.repeat(out, repeat)
        return NDArray(jax.device_put(out, dev), ctx)


def linspace(start, stop, num, endpoint=True, ctx=None, dtype=None):
    import jax
    import jax.numpy as jnp

    dev, ctx = _device(ctx)
    with x64_scope_if(dtype):
        return NDArray(jax.device_put(
            jnp.linspace(start, stop, num, endpoint=endpoint,
                         dtype=np_dtype(dtype or "float32")), dev), ctx)


def eye(N, M=0, k=0, ctx=None, dtype=None):
    import jax
    import jax.numpy as jnp

    dev, ctx = _device(ctx)
    with x64_scope_if(dtype):
        return NDArray(jax.device_put(
            jnp.eye(N, M or None, k, np_dtype(dtype)), dev), ctx)


def from_numpy(a, zero_copy=False):
    return array(a)


def from_dlpack(capsule):
    import jax

    return NDArray(jax.dlpack.from_dlpack(capsule))


def concatenate(arrays, axis=0, always_copy=True):
    return _register.invoke_registered("concat", tuple(arrays),
                                      {"dim": axis})


def waitall():
    from .. import engine

    engine.wait_all()


def moveaxis(a, source, destination):
    return a._apply(lambda d: _jnp().moveaxis(d, source, destination))


def _jnp():
    import jax.numpy as jnp

    return jnp


# control-flow higher-order ops (reference keeps them under contrib)
from ..ops.control_flow import foreach, while_loop, cond  # noqa: E402

# generated op wrappers → module namespace
_register.populate(globals())

# sub-namespaces
from . import random  # noqa: E402
from . import linalg  # noqa: E402
from . import contrib  # noqa: E402
from . import sparse  # noqa: E402

# storage-type dispatch for dot/cast_storage lives at the invoke layer
# (ndarray/register.py _stype_dispatch, the FComputeEx analog), so EVERY
# entry point — nd.dot, NDArray.__matmul__, invoke_registered — routes a
# CSR lhs to the compact kernels instead of densifying at unwrap.
