"""Generated NDArray op wrappers + the eager invoke path.

Reference parity: python/mxnet/ndarray/register.py (import-time codegen over
MXListAllOpNames) + src/imperative/imperative.cc Imperative::Invoke.

The wrapper is polymorphic:
- NDArray inputs → eager path: unwrap, run the pure op (JAX dispatches it
  asynchronously — the engine analog), wrap outputs; when autograd is
  recording and an input is on the tape, record a TapeNode holding the
  jax.vjp pullback.
- jax arrays / tracers → pure pass-through, so the same `mx.nd.*` surface
  works inside `hybridize()` traces and user jit code.
"""

from __future__ import annotations

import sys

from .. import autograd as _ag
from .. import profiler as _prof
from ..base import MXNetError as _MXNetError
from ..ops import registry as _registry
from ..ops.registry import OpDef
from .ndarray import NDArray, _from_jax


_EAGER_OP_TRACE = 0


def in_eager_op_trace():
    """True while an op body is being traced by the EAGER autograd path's
    per-op jax.vjp (as opposed to an enclosing user/CachedOp jit).  Mesh
    ops (ring/ulysses) use this to know their tracer inputs carry
    committed single-device primals that must be resharded in and brought
    back out."""
    return _EAGER_OP_TRACE > 0


class _eager_op_trace:
    def __enter__(self):
        global _EAGER_OP_TRACE
        _EAGER_OP_TRACE += 1

    def __exit__(self, *exc):
        global _EAGER_OP_TRACE
        _EAGER_OP_TRACE -= 1


def _inject(opdef: OpDef, kwargs: dict) -> dict:
    if opdef.mode_dependent and kwargs.get("_is_training") is None:
        kwargs = dict(kwargs)
        kwargs["_is_training"] = _ag.is_training()
    if opdef.random and kwargs.get("_key") is None:
        from ..random import next_key

        kwargs = dict(kwargs)
        kwargs["_key"] = next_key()
    return kwargs


# FComputeEx analog (reference: storage-type dispatch in
# imperative_utils.h): ops with a compact sparse kernel route there
# BEFORE the generic unwrap densifies the sparse operand.  Keyed by op
# name; the handler receives (args, kwargs) with NDArrays intact.
def _stype_dispatch(opdef, args, kwargs):
    if not args or not isinstance(args[0], NDArray):
        return None
    if opdef.name == "dot":
        from .sparse import CSRNDArray
        from .sparse import dot as sparse_dot

        if isinstance(args[0], CSRNDArray):
            # transpose flags may arrive positionally (dot(lhs, rhs,
            # transpose_a, transpose_b) — same order as the dense op)
            extras = args[2:4]
            ta = extras[0] if len(extras) > 0 else kwargs.get(
                "transpose_a", False)
            tb = extras[1] if len(extras) > 1 else kwargs.get(
                "transpose_b", False)
            return sparse_dot(args[0], args[1], transpose_a=ta,
                              transpose_b=tb)
    elif opdef.name.lower() == "cast_storage":
        from .sparse import cast_storage as sparse_cast

        stype = kwargs.get("stype", args[1] if len(args) > 1
                           else "default")
        return sparse_cast(args[0], stype)
    elif opdef.name in ("elemwise_add", "broadcast_add", "add",
                        "elemwise_mul", "broadcast_mul", "multiply") \
            and len(args) >= 2:
        from .sparse import RowSparseNDArray
        from .sparse import _on_eager_tape
        from .sparse import add as rsp_add
        from .sparse import elemwise_mul as rsp_mul

        if isinstance(args[0], RowSparseNDArray) and \
                isinstance(args[1], RowSparseNDArray) and \
                not _on_eager_tape(args[0], args[1]):
            fn = rsp_add if "add" in opdef.name else rsp_mul
            return fn(args[0], args[1])
    return None


def invoke(opdef: OpDef, args: tuple, kwargs: dict):
    # frontend-only kwargs accepted by every reference op wrapper
    out_arr = kwargs.pop("out", None)
    req_ctx = kwargs.pop("ctx", None)
    name = kwargs.pop("name", None)  # symbol-compat: ignored eagerly
    sparse_out = _stype_dispatch(opdef, args, kwargs)
    if sparse_out is not None:
        if out_arr is not None or req_ctx is not None:
            from .sparse import BaseSparseNDArray

            if isinstance(sparse_out, BaseSparseNDArray):
                raise _MXNetError(
                    f"{opdef.name}: out=/ctx= unsupported when the "
                    "result has sparse storage")
            return _finalize(sparse_out, out_arr, req_ctx)
        return sparse_out
    kwargs = _inject(opdef, kwargs)
    fn = opdef.fn
    if _prof._S.running:  # cheap flag read on the hot path
        with _prof.op_span(opdef.name):
            result = _invoke_inner(opdef, fn, args, kwargs)
            if _prof.want_sync():
                _block_result(result)
    else:
        result = _invoke_inner(opdef, fn, args, kwargs)
    if out_arr is not None or req_ctx is not None:
        return _finalize(result, out_arr, req_ctx)
    return result


def _block_result(result):
    items = result if isinstance(result, (tuple, list)) else (result,)
    for r in items:
        data = getattr(r, "_data", r)
        if hasattr(data, "block_until_ready"):
            data.block_until_ready()


def _finalize(result, out_arr, req_ctx):
    import jax

    if req_ctx is not None and isinstance(result, NDArray):
        result = result.as_in_context(req_ctx)
    if out_arr is not None:
        src = result[0] if isinstance(result, tuple) else result
        if isinstance(src, NDArray):
            out_arr._adopt(src)  # keeps the tape position (out= records too)
        else:
            out_arr._data = src
            out_arr._version += 1
        return out_arr
    return result


def _invoke_inner(opdef: OpDef, fn, args: tuple, kwargs: dict):
    if opdef.opaque:
        return fn(*args, **kwargs)  # host-level op: handles NDArrays itself

    slots = [i for i, a in enumerate(args) if isinstance(a, NDArray)]
    kslots = [k for k, v in kwargs.items() if isinstance(v, NDArray)]
    if not slots and not kslots:
        # pure path if any user arg is a jax array/tracer, or any injected
        # arg (e.g. _key under a traced key_scope) is a tracer
        if _any_jax(args) or _any_jax(
                v for k, v in kwargs.items() if not k.startswith("_")) or \
                _any_tracer(kwargs.values()):
            return fn(*args, **kwargs)
        # creation-style op called eagerly (no array inputs): wrap output
        out = fn(*args, **kwargs)
        if isinstance(out, (tuple, list)):
            return tuple(_wrap(o, None) for o in out)
        return _wrap(out, None)

    nd_list = [args[i] for i in slots] + [kwargs[k] for k in kslots]
    arrs = [x._data for x in nd_list]

    def pure_fn(*raw):
        a2 = list(args)
        k2 = dict(kwargs)
        it = iter(raw)
        for i in slots:
            a2[i] = next(it)
        for k in kslots:
            k2[k] = next(it)
        return fn(*a2, **k2)

    ctx = nd_list[0]._ctx
    recording = _ag.is_recording() and any(x._on_tape() for x in nd_list)
    if recording:
        import jax

        with _eager_op_trace():
            out, vjp_fn = jax.vjp(pure_fn, *arrs)
        single = not isinstance(out, (tuple, list))
        outs_j = [out] if single else list(out)
        outs = [_wrap(o, ctx) for o in outs_j]
        node = _ag.TapeNode(vjp_fn, nd_list, outs, name=opdef.name,
                            pure_fn=pure_fn)
        for o in outs:
            if isinstance(o, NDArray):
                o._tape_node = node
        return outs[0] if single else tuple(outs)

    out = pure_fn(*arrs)
    if isinstance(out, (tuple, list)):
        return tuple(_wrap(o, ctx) for o in out)
    return _wrap(out, ctx)


def _any_jax(xs) -> bool:
    import jax

    return any(isinstance(x, (jax.Array, jax.core.Tracer)) for x in xs)


def _any_tracer(xs) -> bool:
    import jax

    return any(isinstance(x, jax.core.Tracer) for x in xs)


def _wrap(o, ctx):
    from .. import engine

    if hasattr(o, "shape") and hasattr(o, "dtype"):
        return _from_jax(engine.maybe_sync(o), ctx)
    return o


def invoke_registered(name: str, args: tuple, kwargs: dict):
    return invoke(_registry.get(name), args, kwargs)


def invoke_simple(fn, args: tuple, kwargs: dict | None = None, name=""):
    """Invoke an unregistered pure function with full NDArray/tape handling
    (used for indexing and other ad-hoc dunder ops)."""
    return invoke(OpDef(name or getattr(fn, "__name__", "fn"), fn),
                  args, kwargs or {})


def _make_wrapper(opdef: OpDef):
    def wrapper(*args, **kwargs):
        return invoke(opdef, args, kwargs)

    wrapper.__name__ = opdef.name
    wrapper.__qualname__ = opdef.name
    wrapper.__doc__ = (opdef.fn.__doc__ or "") + \
        f"\n\n(generated NDArray wrapper for op '{opdef.name}')"
    return wrapper


def populate(namespace: dict, names=None):
    """Generate wrappers for every registered op into `namespace`
    (reference: _init_ops in python/mxnet/ndarray/register.py)."""
    for name, opdef in _registry.all_ops().items():
        if names is not None and name not in names:
            continue
        if name not in namespace:
            namespace[name] = _make_wrapper(opdef)


# NDArray instance methods generated from ops (mx.nd.NDArray method surface).
_METHOD_OPS = [
    "sum", "mean", "prod", "max", "min", "argmax", "argmin", "norm",
    "transpose", "flatten", "expand_dims", "squeeze", "clip", "abs",
    "exp", "log", "sqrt", "square", "sigmoid", "tanh", "relu", "softmax",
    "log_softmax", "slice_axis", "take", "flip", "tile", "repeat", "pad",
    "round", "floor", "ceil", "split", "one_hot", "topk", "sort", "argsort",
    "swapaxes", "broadcast_to", "broadcast_like", "slice_like", "sign",
    "zeros_like", "ones_like", "stop_gradient", "diag", "cumsum",
]


def _attach_methods():
    for name in _METHOD_OPS:
        if name in _registry.all_ops() and not hasattr(NDArray, name):
            opdef = _registry.get(name)

            def method(self, *a, _opdef=opdef, **kw):
                return invoke(_opdef, (self,) + a, kw)

            method.__name__ = name
            setattr(NDArray, name, method)


_attach_methods()
