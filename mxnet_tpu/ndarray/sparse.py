"""mx.nd.sparse — sparse NDArray storage and API surface.

Reference parity: python/mxnet/ndarray/sparse.py (RowSparseNDArray,
CSRNDArray, row_sparse_array, csr_matrix) over src/ndarray/ndarray.cc's
sparse chunks.

TPU-first design: XLA has no sparse buffer layout and the MXU wants
dense tiles, so sparse COMPUTE densifies at the op boundary (any dense
op touching a sparse array reads a scattered dense view).  Sparse
STORAGE, however, is real and compact:

- ``RowSparseNDArray`` holds (indices (K,), values (K, cols...)) plus
  the logical shape — O(K) device memory, never O(rows), until an op
  explicitly materializes a dense view;
- Embedding(sparse_grad=True) produces a compact row-sparse gradient on
  the eager tape (O(touched rows), the reference's key memory/comm
  optimization for big embeddings), and the optimizer layer performs
  the reference's lazy row-wise update straight from the compact parts;
- KVStore.row_sparse_pull gathers only the requested rows.

Under jit (hybridize / ShardedTrainer) gradients stay dense: XLA's
scatter-add transpose of the gather IS the fused row-update — compact
storage there would only add host round-trips.
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _from_jax


class _RowSparseCt:
    """Row-sparse cotangent flowing through the autograd tape.

    Indices may repeat (accumulation concatenates; coalescing happens
    once, when the gradient buffer is written).
    """

    __slots__ = ("indices", "values", "shape")

    def __init__(self, indices, values, shape):
        self.indices = indices      # jax (K,) int32
        self.values = values        # jax (K, cols...)
        self.shape = tuple(shape)   # logical dense shape

    @property
    def dtype(self):
        return self.values.dtype

    def astype(self, dtype):
        return _RowSparseCt(self.indices, self.values.astype(dtype),
                            self.shape)

    def to_dense(self):
        import jax.numpy as jnp

        base = jnp.zeros(self.shape, self.values.dtype)
        return base.at[self.indices].add(self.values)

    def coalesce(self):
        """Merge duplicate indices (sorted unique + segment-sum)."""
        import jax
        import jax.numpy as jnp

        uniq, inv = jnp.unique(self.indices, return_inverse=True)
        vals = jax.ops.segment_sum(self.values, inv.reshape(-1),
                                   num_segments=uniq.shape[0])
        return _RowSparseCt(uniq, vals, self.shape)

    def __add__(self, other):
        import jax.numpy as jnp

        if isinstance(other, _RowSparseCt):
            return _RowSparseCt(
                jnp.concatenate([self.indices, other.indices]),
                jnp.concatenate([self.values, other.values]), self.shape)
        return self.to_dense() + other

    __radd__ = __add__


def _sparsify_rows(arr):
    """Dense (R, cols...) -> (indices, values) of nonzero rows, computed
    on device (no host round-trip of the full table)."""
    import jax.numpy as jnp

    arr = jnp.asarray(arr)
    mask = jnp.any(arr.reshape(arr.shape[0], -1) != 0, axis=1)
    idx = jnp.nonzero(mask)[0].astype(jnp.int32)   # eager: concrete size
    return idx, jnp.take(arr, idx, axis=0)


def _sparsify_csr(a):
    """Dense 2-D numpy -> (data, indices, indptr) numpy components."""
    a = _np.asarray(a)
    counts = (a != 0).sum(axis=1)
    return (a[a != 0], _np.nonzero(a)[1].astype(_np.int32),
            _np.concatenate([[0], _np.cumsum(counts)]).astype(_np.int32))


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray(BaseSparseNDArray):
    """True compact row-sparse array: (indices, values) + logical shape.

    Dense ops still work — ``_data`` is a property that materializes a
    scattered dense view on demand — but storage stays O(K) until then.
    """

    __slots__ = ("_rs_indices", "_rs_values", "_logical_shape")

    def __init__(self, indices, values, shape, ctx=None):
        import jax.numpy as jnp

        # NDArray.__init__ not called: _data is compact-backed here
        self._rs_indices = jnp.asarray(indices, jnp.int32)
        self._rs_values = jnp.asarray(values)
        self._logical_shape = tuple(int(s) for s in shape)
        self._ctx = ctx
        self._version = 0
        self._grad = None
        self._grad_req = "null"
        self._tape_node = None
        self._stype = "row_sparse"

    # -- compact accessors (no densification) ----------------------------------
    @property
    def indices(self):
        return _from_jax(self._rs_indices)

    @property
    def data(self):
        return _from_jax(self._rs_values)

    @property
    def num_stored_rows(self):
        return int(self._rs_indices.shape[0])

    # -- dense view ------------------------------------------------------------
    @property
    def _data(self):
        import jax.numpy as jnp

        base = jnp.zeros(self._logical_shape, self._rs_values.dtype)
        return base.at[self._rs_indices].add(self._rs_values)

    @_data.setter
    def _data(self, jarr):
        self._set_data(jarr)

    @property
    def shape(self):
        return self._logical_shape

    @property
    def dtype(self):
        dt = self._rs_values.dtype
        return dt.type if hasattr(dt, "type") and \
            dt.type.__module__ == "numpy" else dt

    @property
    def size(self):
        n = 1
        for s in self._logical_shape:
            n *= s
        return n

    @property
    def ndim(self):
        return len(self._logical_shape)

    def _set_data(self, jarr):
        """Dense write-back: re-sparsify (nonzero rows, on device)."""
        if isinstance(jarr, _RowSparseCt):
            self._set_sparse(jarr.indices, jarr.values)
            return
        idx, vals = _sparsify_rows(jarr)
        self._rs_indices = idx
        self._rs_values = vals
        self._logical_shape = tuple(int(s) for s in jarr.shape)
        self._version += 1

    def _set_sparse(self, indices, values):
        import jax.numpy as jnp

        self._rs_indices = jnp.asarray(indices, jnp.int32)
        self._rs_values = jnp.asarray(values)
        self._version += 1

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        return self

    def copy(self):
        return RowSparseNDArray(self._rs_indices, self._rs_values,
                                self._logical_shape, self._ctx)

    def __repr__(self):
        return (f"\n<RowSparseNDArray {self._logical_shape} "
                f"({self.num_stored_rows} stored rows)>")


class CSRNDArray(BaseSparseNDArray):
    """Compact CSR: (data, indices, indptr) + logical shape (the I/O
    format — LibSVMIter and scipy interop)."""

    __slots__ = ("_csr_data", "_csr_indices", "_csr_indptr",
                 "_logical_shape")

    def __init__(self, data, indices, indptr, shape, ctx=None):
        import jax.numpy as jnp

        self._csr_data = jnp.asarray(data)
        self._csr_indices = jnp.asarray(indices, jnp.int32)
        self._csr_indptr = jnp.asarray(indptr, jnp.int32)
        self._logical_shape = tuple(int(s) for s in shape)
        self._ctx = ctx
        self._version = 0
        self._grad = None
        self._grad_req = "null"
        self._tape_node = None
        self._stype = "csr"

    @property
    def indptr(self):
        return _from_jax(self._csr_indptr)

    @property
    def indices(self):
        return _from_jax(self._csr_indices)

    @property
    def data(self):
        return _from_jax(self._csr_data)

    @property
    def _data(self):
        import jax.numpy as jnp

        n_rows, n_cols = self._logical_shape
        indptr = _np.asarray(self._csr_indptr)
        rows = _np.repeat(_np.arange(n_rows), _np.diff(indptr))
        base = jnp.zeros(self._logical_shape, self._csr_data.dtype)
        return base.at[jnp.asarray(rows),
                       self._csr_indices].set(self._csr_data)

    @_data.setter
    def _data(self, jarr):
        self._set_data(jarr)

    @property
    def shape(self):
        return self._logical_shape

    @property
    def dtype(self):
        dt = self._csr_data.dtype
        return dt.type if hasattr(dt, "type") and \
            dt.type.__module__ == "numpy" else dt

    @property
    def size(self):
        return self._logical_shape[0] * self._logical_shape[1]

    @property
    def ndim(self):
        return 2

    def _set_data(self, jarr):
        import jax.numpy as jnp

        a = _np.asarray(jarr)
        data, indices, indptr = _sparsify_csr(a)
        self._csr_data = jnp.asarray(data)
        self._csr_indices = jnp.asarray(indices)
        self._csr_indptr = jnp.asarray(indptr)
        self._logical_shape = tuple(a.shape)
        self._version += 1

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        return self

    def __repr__(self):
        return (f"\n<CSRNDArray {self._logical_shape} "
                f"({int(self._csr_data.shape[0])} stored values)>")


# -- constructors --------------------------------------------------------------

def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, RowSparseNDArray):
        return arg1
    if isinstance(arg1, (tuple, list)) and len(arg1) == 2 \
            and not isinstance(arg1[0], (int, float)):
        data, indices = arg1
        data = _np.asarray(getattr(data, "asnumpy", lambda: data)(),
                           dtype=dtype)
        indices = _np.asarray(
            getattr(indices, "asnumpy", lambda: indices)()).astype(
            _np.int64)
        full_shape = shape or (
            ((int(indices.max()) + 1,) + data.shape[1:]) if len(indices)
            else (0,) + data.shape[1:])
        return RowSparseNDArray(indices, data, full_shape, ctx)
    # dense input: sparsify (on device when already an NDArray)
    raw = arg1._data if isinstance(arg1, NDArray) else _np.asarray(
        arg1, dtype=dtype or "float32")
    idx, vals = _sparsify_rows(raw)
    return RowSparseNDArray(idx, vals, raw.shape, ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    if isinstance(arg1, CSRNDArray):
        return arg1
    if isinstance(arg1, (tuple, list)) and len(arg1) == 3:
        data, indices, indptr = (
            _np.asarray(getattr(x, "asnumpy", lambda x=x: x)())
            for x in arg1)
        n_rows = len(indptr) - 1
        n_cols = shape[1] if shape else int(indices.max()) + 1
        return CSRNDArray(data.astype(dtype or data.dtype), indices,
                          indptr, (n_rows, n_cols), ctx)
    a = _np.asarray(getattr(arg1, "asnumpy", lambda: arg1)())
    # preserve the input dtype (reference cast_storage round-trips any
    # dtype); only force float32 for dtype-less python lists
    a = a.astype(dtype or (a.dtype if a.dtype != _np.object_
                           else "float32"))
    data, indices, indptr = _sparsify_csr(a)
    return CSRNDArray(data, indices, indptr, a.shape, ctx)


# -- sparse COMPUTE (VERDICT r3 task #5) ---------------------------------------
#
# dot(csr, dense) and dot(csrᵀ, dense) as jit-able gather + segment-sum /
# scatter-add — the TPU formulation of the reference's CSR kernels
# (src/operator/tensor/dot.cc DotCsrDnsDns / DotCsrTransDnsDns): no
# (rows × cols) dense view of the sparse matrix is ever materialized;
# compute is O(nnz · D).


def _csr_rows_of(indptr, nnz):
    """jit-able (nnz,) row id per stored value: row r owns positions
    indptr[r] <= p < indptr[r+1]."""
    import jax.numpy as jnp

    return (jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1) \
        .astype(jnp.int32)


def csr_dot_dense(data, indices, indptr, rhs, out_rows,
                  transpose_a=False):
    """Pure-function CSR @ dense (jit-able, static nnz).

    data (nnz,), indices (nnz,), indptr (rows+1,), rhs 2-D.
    transpose_a=False: (rows, C) @ (C, D) -> (rows, D), out_rows=rows.
    transpose_a=True:  (rows, C)ᵀ @ (rows, D) -> (C, D), out_rows=C.
    """
    import jax
    import jax.numpy as jnp

    nnz = data.shape[0]
    rows = _csr_rows_of(indptr, nnz)
    if transpose_a:
        contrib = data[:, None] * rhs[rows]              # (nnz, D)
        out = jnp.zeros((out_rows, rhs.shape[1]), contrib.dtype)
        return out.at[indices].add(contrib)
    gathered = data[:, None] * jnp.take(rhs, indices, axis=0)
    return jax.ops.segment_sum(gathered, rows, num_segments=out_rows)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (reference: mx.nd.sparse.dot over
    src/operator/tensor/dot.cc).  CSR lhs runs the compact kernels
    above; the backward is compact too (dRhs = csrᵀ @ dy — never a
    dense view of lhs).  Dense lhs falls through to the dense op."""
    from .register import invoke_registered

    if not isinstance(lhs, CSRNDArray):
        return invoke_registered(
            "dot", (lhs, rhs),
            {"transpose_a": transpose_a, "transpose_b": transpose_b})
    if transpose_b:
        raise MXNetError("sparse.dot: transpose_b unsupported for CSR "
                         "lhs (reference parity: dot.cc has no "
                         "CsrDns^T kernel)")
    from .. import autograd as _ag

    n_rows, n_cols = lhs._logical_shape
    need = n_rows if transpose_a else n_cols
    if rhs.shape[0] != need:
        # explicit check: a wrong rhs would otherwise gather/scatter
        # out-of-bounds, which jax CLAMPS instead of raising
        raise MXNetError(
            f"sparse.dot: shape mismatch {lhs._logical_shape}"
            f"{'ᵀ' if transpose_a else ''} @ {tuple(rhs.shape)}")
    out_rows = n_cols if transpose_a else n_rows

    class _Fn(_ag.Function):
        def forward(self, lhs_, rhs_):
            self._parts = (lhs_._csr_data, lhs_._csr_indices,
                           lhs_._csr_indptr)
            y = csr_dot_dense(*self._parts, rhs_._data, out_rows,
                              transpose_a)
            return _from_jax(y)

        def backward(self, g):
            # dRhs: flip the transpose — still a compact kernel
            drhs = csr_dot_dense(
                *self._parts, g._data,
                n_rows if transpose_a else n_cols,
                not transpose_a)
            return None, _from_jax(drhs)

    return _Fn()(lhs, rhs)


def _coalesced_parts(rsp):
    ct = _RowSparseCt(rsp._rs_indices, rsp._rs_values,
                      rsp._logical_shape).coalesce()
    return ct.indices, ct.values


def _on_eager_tape(*arrs):
    """True when autograd is recording and an operand is on the tape —
    the compact fast paths below do not record, so they must defer to
    the dense op (which does) rather than silently drop gradients."""
    from .. import autograd as _ag

    return _ag.is_recording() and any(
        getattr(a, "_on_tape", lambda: False)() for a in arrs)


def _select_stored_rows(idx_sorted, wanted_sorted):
    """Positions (host numpy) of idx_sorted entries present in
    wanted_sorted — the one row-intersection helper retain and
    elemwise_mul share."""
    mask = _np.isin(_np.asarray(idx_sorted), _np.asarray(wanted_sorted))
    return _np.nonzero(mask)[0]


def add(lhs, rhs):
    """Compact row-sparse add (reference: mx.nd.sparse.add /
    elemwise_add FComputeEx rsp+rsp kernel): concat + coalesce —
    O(K1+K2), never a dense row-dim buffer.  Mixed sparse/dense — or
    operands on the autograd tape (the compact path doesn't record) —
    fall back to the dense op."""
    if isinstance(lhs, RowSparseNDArray) and \
            isinstance(rhs, RowSparseNDArray) and \
            not _on_eager_tape(lhs, rhs):
        if lhs._logical_shape != rhs._logical_shape:
            raise MXNetError(
                f"sparse.add: shape mismatch {lhs._logical_shape} vs "
                f"{rhs._logical_shape}")
        import jax.numpy as jnp

        dt = jnp.promote_types(lhs.dtype, rhs.dtype)
        ct = _RowSparseCt(
            jnp.concatenate([lhs._rs_indices, rhs._rs_indices]),
            jnp.concatenate([lhs._rs_values.astype(dt),
                             rhs._rs_values.astype(dt)]),
            lhs._logical_shape).coalesce()
        return RowSparseNDArray(ct.indices, ct.values,
                                lhs._logical_shape, lhs._ctx)
    from .register import invoke_registered

    return invoke_registered("elemwise_add", (lhs, rhs), {})


def elemwise_mul(lhs, rhs):
    """Compact row-sparse multiply: the result's rows are the
    INTERSECTION of stored rows (reference: elemwise_mul rsp·rsp).
    Tape-recorded operands fall back dense, as in add()."""
    if not (isinstance(lhs, RowSparseNDArray)
            and isinstance(rhs, RowSparseNDArray)) \
            or _on_eager_tape(lhs, rhs):
        from .register import invoke_registered

        return invoke_registered("elemwise_mul", (lhs, rhs), {})
    if lhs._logical_shape != rhs._logical_shape:
        raise MXNetError(
            f"sparse.elemwise_mul: shape mismatch {lhs._logical_shape} "
            f"vs {rhs._logical_shape}")
    import jax.numpy as jnp

    li, lv = _coalesced_parts(lhs)
    ri, rv = _coalesced_parts(rhs)
    dt = jnp.promote_types(lhs.dtype, rhs.dtype)
    if int(ri.shape[0]) == 0 or int(li.shape[0]) == 0:
        return zeros("row_sparse", lhs._logical_shape, lhs._ctx, dt)
    keep = _select_stored_rows(li, ri)
    idx = jnp.asarray(keep, jnp.int32)
    out_rows = jnp.take(li, idx)
    # position of each kept l-row inside r (both sorted post-coalesce)
    rpos = jnp.searchsorted(ri, out_rows)
    out_vals = jnp.take(lv, idx, axis=0).astype(dt) * jnp.take(
        rv, rpos, axis=0).astype(dt)
    return RowSparseNDArray(out_rows, out_vals, lhs._logical_shape,
                            lhs._ctx)


def retain(arr, indices):
    """Keep only the requested rows of a RowSparseNDArray (reference:
    mx.nd.sparse.retain, the kvstore row_sparse_pull primitive) —
    compact in, compact out."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("sparse.retain expects a RowSparseNDArray")
    import jax.numpy as jnp

    want = _np.unique(_np.asarray(
        getattr(indices, "asnumpy", lambda: indices)()).astype(
        _np.int64).ravel())
    si, sv = _coalesced_parts(arr)
    keep = _select_stored_rows(si, want)
    idx = jnp.asarray(keep, jnp.int32)
    return RowSparseNDArray(jnp.take(si, idx),
                            jnp.take(sv, idx, axis=0),
                            arr._logical_shape, arr._ctx)


def cast_storage(arr, stype):
    """Real storage casting at the NDArray level (reference:
    mx.nd.cast_storage, src/operator/tensor/cast_storage.cc): produces
    actual compact CSR/RowSparse arrays, not a dense tagged view."""
    if stype == "default":
        return arr.tostype("default") if isinstance(
            arr, BaseSparseNDArray) else arr
    if stype == "csr":
        if isinstance(arr, CSRNDArray):
            return arr
        return csr_matrix(arr)
    if stype == "row_sparse":
        if isinstance(arr, RowSparseNDArray):
            return arr
        return row_sparse_array(arr)
    raise MXNetError(f"cast_storage: unknown stype {stype!r}")


def zeros(stype, shape, ctx=None, dtype=None):
    import jax.numpy as jnp

    from . import zeros as dense_zeros

    dtype = dtype or "float32"
    if stype == "row_sparse":
        return RowSparseNDArray(
            jnp.zeros((0,), jnp.int32),
            jnp.zeros((0,) + tuple(shape[1:]), dtype), shape, ctx)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dtype),
                          jnp.zeros((0,), jnp.int32),
                          jnp.zeros((shape[0] + 1,), jnp.int32), shape,
                          ctx)
    return dense_zeros(shape, ctx, dtype)
