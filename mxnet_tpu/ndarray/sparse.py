"""mx.nd.sparse — sparse NDArray API surface.

Reference parity: python/mxnet/ndarray/sparse.py (RowSparseNDArray,
CSRNDArray, row_sparse_array, csr_matrix).

TPU-first design decision: XLA has no sparse buffer layout, and on TPU the
MXU/VPU want dense tiles — the reference's sparse storage exists to optimize
*CPU/PCIe-era* embedding gradients and parameter-server traffic.  Here sparse
arrays are VIEWS carrying stype metadata plus the compressed components,
backed by dense compute.  ``row_sparse`` keeps (indices, values) so
`row_sparse_pull`-style flows and sparse serialization remain expressible;
compute densifies lazily.  This preserves the full API while XLA's
scatter/gather fusion covers the perf case that matters on TPU
(Embedding with sparse_grad lowers to scatter-add, not a dense update).
"""

from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _from_jax


class BaseSparseNDArray(NDArray):
    __slots__ = ()


class RowSparseNDArray(BaseSparseNDArray):
    """Dense-backed row_sparse array; `indices`/`data` recover components."""

    __slots__ = ("_rs_indices",)

    def __init__(self, data, ctx=None, indices=None):
        super().__init__(data, ctx, stype="row_sparse")
        self._rs_indices = indices

    @property
    def indices(self):
        import jax.numpy as jnp

        if self._rs_indices is not None:
            return _from_jax(self._rs_indices)
        nz = _np.nonzero(_np.abs(self.asnumpy()).reshape(
            self.shape[0], -1).sum(axis=1))[0]
        return _from_jax(jnp.asarray(nz.astype(_np.int64)))

    @property
    def data(self):
        import jax.numpy as jnp

        idx = self.indices._data
        return _from_jax(jnp.take(self._data, idx, axis=0))

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        return self


class CSRNDArray(BaseSparseNDArray):
    __slots__ = ()

    def __init__(self, data, ctx=None):
        super().__init__(data, ctx, stype="csr")

    @property
    def indptr(self):
        import jax.numpy as jnp

        a = self.asnumpy()
        counts = (a != 0).sum(axis=1)
        return _from_jax(jnp.asarray(
            _np.concatenate([[0], _np.cumsum(counts)]).astype(_np.int64)))

    @property
    def indices(self):
        import jax.numpy as jnp

        a = self.asnumpy()
        return _from_jax(jnp.asarray(_np.nonzero(a)[1].astype(_np.int64)))

    @property
    def data(self):
        import jax.numpy as jnp

        a = self.asnumpy()
        return _from_jax(jnp.asarray(a[a != 0]))

    def tostype(self, stype):
        if stype == "default":
            return NDArray(self._data, self._ctx)
        return self


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    import jax.numpy as jnp

    if isinstance(arg1, (tuple, list)) and len(arg1) == 2 and not isinstance(
            arg1[0], (int, float)):
        data, indices = arg1
        data = _np.asarray(getattr(data, "asnumpy", lambda: data)())
        indices = _np.asarray(
            getattr(indices, "asnumpy", lambda: indices)()).astype(_np.int64)
        full_shape = shape or ((int(indices.max()) + 1,) + data.shape[1:]
                               if len(indices) else (0,) + data.shape[1:])
        dense = _np.zeros(full_shape, dtype=dtype or data.dtype)
        dense[indices] = data
        return RowSparseNDArray(jnp.asarray(dense),
                                indices=jnp.asarray(indices))
    a = _np.asarray(getattr(arg1, "asnumpy", lambda: arg1)(),
                    dtype=dtype or "float32")
    return RowSparseNDArray(jnp.asarray(a))


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    import jax.numpy as jnp

    if isinstance(arg1, (tuple, list)) and len(arg1) == 3:
        data, indices, indptr = (
            _np.asarray(getattr(x, "asnumpy", lambda x=x: x)())
            for x in arg1)
        n_rows = len(indptr) - 1
        n_cols = shape[1] if shape else int(indices.max()) + 1
        dense = _np.zeros((n_rows, n_cols), dtype=dtype or data.dtype)
        for r in range(n_rows):
            for j in range(int(indptr[r]), int(indptr[r + 1])):
                dense[r, int(indices[j])] = data[j]
        return CSRNDArray(jnp.asarray(dense))
    a = _np.asarray(getattr(arg1, "asnumpy", lambda: arg1)(),
                    dtype=dtype or "float32")
    return CSRNDArray(jnp.asarray(a))


def zeros(stype, shape, ctx=None, dtype=None):
    from . import zeros as dense_zeros

    base = dense_zeros(shape, ctx, dtype)
    if stype == "row_sparse":
        return RowSparseNDArray(base._data, base._ctx)
    if stype == "csr":
        return CSRNDArray(base._data, base._ctx)
    return base
