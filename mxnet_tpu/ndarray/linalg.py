"""mx.nd.linalg — linear algebra namespace (python/mxnet/ndarray/linalg.py).

Op names drop the `linalg_` prefix, matching the reference namespace.
"""

from __future__ import annotations

from ..ops import registry as _registry
from . import register as _register


def _alias(public, opname):
    opdef = _registry.get(opname)

    def f(*args, **kwargs):
        return _register.invoke(opdef, args, kwargs)

    f.__name__ = public
    return f


gemm = _alias("gemm", "linalg_gemm")
gemm2 = _alias("gemm2", "linalg_gemm2")
potrf = _alias("potrf", "linalg_potrf")
potri = _alias("potri", "linalg_potri")
trsm = _alias("trsm", "linalg_trsm")
trmm = _alias("trmm", "linalg_trmm")
syrk = _alias("syrk", "linalg_syrk")
gelqf = _alias("gelqf", "linalg_gelqf")
syevd = _alias("syevd", "linalg_syevd")
sumlogdiag = _alias("sumlogdiag", "linalg_sumlogdiag")
extractdiag = _alias("extractdiag", "linalg_extractdiag")
makediag = _alias("makediag", "linalg_makediag")
extracttrian = _alias("extracttrian", "linalg_extracttrian")
maketrian = _alias("maketrian", "linalg_maketrian")
inverse = _alias("inverse", "linalg_inverse")
det = _alias("det", "linalg_det")
slogdet = _alias("slogdet", "linalg_slogdet")
svd = _alias("svd", "linalg_svd")
