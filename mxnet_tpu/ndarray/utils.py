"""NDArray serialization: mx.nd.save / mx.nd.load.

Reference parity: src/ndarray/ndarray.cc NDArray::Save/Load + the
kMXAPINDArrayListMagic container written by MXNDArraySave (src/c_api/c_api.cc).
Format (little-endian), best-effort byte-compatible with the reference's
``.params`` files so upstream model-zoo weights load directly:

  container:  uint64 0x112 (kMXAPINDArrayListMagic), uint64 reserved=0,
              uint64 n_arrays, n_arrays × NDArray records,
              uint64 n_names, n_names × (uint64 len, bytes) names
  ndarray:    uint32 0xF993fac9 (NDARRAY_V2_MAGIC), int32 stype (-1 dense),
              uint32 ndim, int64[ndim] shape, int32 dev_type, int32 dev_id,
              int32 type_flag, raw data bytes
  sparse:     uint32 0xF993facA (OUR extension magic — upstream's v2
              sparse layout differs and cannot be byte-verified against
              the empty mount, so fork records use a distinct magic and
              upstream sparse files still fail with a clean error),
              int32 stype (1 row_sparse, 2 csr), uint32 ndim,
              int64[ndim] logical shape, int32 dev_type, int32 dev_id,
              int32 type_flag, then
                row_sparse: uint64 K, int64[K] indices, raw values
                csr:        uint64 nnz, int64[nnz] indices,
                            uint64 P, int64[P] indptr, raw data

NOTE: the reference mount was empty at survey time (SURVEY.md preamble);
dense field order follows upstream apache/incubator-mxnet 1.x and must
be re-verified against the fork if the mount is populated.
"""

from __future__ import annotations

import struct

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _from_jax

_LIST_MAGIC = 0x112
_ND_MAGIC_V2 = 0xF993FAC9
_ND_MAGIC_V1 = 0xF993FAC8
_ND_MAGIC_SPARSE = 0xF993FACA  # fork extension (see module docstring)

# reference type flags (mshadow/base.h)
_TYPE_FLAGS = {
    _np.dtype("float32"): 0, _np.dtype("float64"): 1,
    _np.dtype("float16"): 2, _np.dtype("uint8"): 3,
    _np.dtype("int32"): 4, _np.dtype("int8"): 5, _np.dtype("int64"): 6,
}
_FLAG_TYPES = {v: k for k, v in _TYPE_FLAGS.items()}
_BF16_FLAG = 12  # extension flag for bfloat16 (not in 1.x reference)


_STYPE_ROW_SPARSE = 1
_STYPE_CSR = 2


def _write_header(f, magic, stype, shape, flag):
    f.write(struct.pack("<I", magic))
    f.write(struct.pack("<i", stype))
    f.write(struct.pack("<I", len(shape)))
    if shape:
        f.write(struct.pack(f"<{len(shape)}q", *shape))
    f.write(struct.pack("<ii", 1, 0))  # context: cpu(0), stripped on save
    f.write(struct.pack("<i", flag))


def _read_flag_values(f, flag, n_elems, shape):
    """Decode n_elems values of the given type flag into a jnp array."""
    import jax.numpy as jnp

    if flag == _BF16_FLAG:
        raw = _np.frombuffer(f.read(2 * n_elems), dtype=_np.uint16)
        return jnp.asarray(raw).view(jnp.bfloat16).reshape(shape)
    dt = _FLAG_TYPES[flag]
    raw = _np.frombuffer(f.read(dt.itemsize * n_elems), dtype=dt)
    from ..base import x64_scope_if

    # 64-bit payloads (reference int64/float64 .params): jax's x32
    # default would silently truncate/wrap the loaded values
    with x64_scope_if(dt):
        return jnp.asarray(raw.reshape(shape))


def _flag_and_raw(a):
    dt = a.dtype
    if dt.name == "bfloat16":
        return _BF16_FLAG, a.view(_np.uint16)
    if dt == _np.dtype("bool"):
        a = a.astype("uint8")
        return _TYPE_FLAGS[a.dtype], a
    if dt not in _TYPE_FLAGS:
        a = a.astype("float32")
    return _TYPE_FLAGS[a.dtype], a


def _save_ndarray(f, arr: NDArray):
    from .sparse import CSRNDArray, RowSparseNDArray

    if isinstance(arr, RowSparseNDArray):
        # compact record: a (10M, 512) embedding with 4k touched rows
        # writes 4k rows, not 10M (reference: sparse NDArray::Save)
        vals = _np.asarray(arr._rs_values)
        idx = _np.asarray(arr._rs_indices, dtype=_np.int64)
        flag, raw = _flag_and_raw(vals)
        _write_header(f, _ND_MAGIC_SPARSE, _STYPE_ROW_SPARSE,
                      arr._logical_shape, flag)
        f.write(struct.pack("<Q", idx.shape[0]))
        f.write(idx.tobytes())
        f.write(raw.tobytes())
        return
    if isinstance(arr, CSRNDArray):
        data = _np.asarray(arr._csr_data)
        indices = _np.asarray(arr._csr_indices, dtype=_np.int64)
        indptr = _np.asarray(arr._csr_indptr, dtype=_np.int64)
        flag, raw = _flag_and_raw(data)
        _write_header(f, _ND_MAGIC_SPARSE, _STYPE_CSR,
                      arr._logical_shape, flag)
        f.write(struct.pack("<Q", data.shape[0]))
        f.write(indices.tobytes())
        f.write(struct.pack("<Q", indptr.shape[0]))
        f.write(indptr.tobytes())
        f.write(raw.tobytes())
        return
    a = arr.asnumpy()
    flag, raw = _flag_and_raw(a)
    _write_header(f, _ND_MAGIC_V2, -1, tuple(a.shape), flag)
    f.write(raw.tobytes())


def _load_ndarray(f) -> NDArray:
    import jax.numpy as jnp

    (magic,) = struct.unpack("<I", f.read(4))
    if magic == _ND_MAGIC_SPARSE:
        (stype,) = struct.unpack("<i", f.read(4))
        if stype not in (_STYPE_ROW_SPARSE, _STYPE_CSR):
            raise MXNetError(f"unknown sparse storage type {stype}")
        (ndim,) = struct.unpack("<I", f.read(4))
        shape = struct.unpack(f"<{ndim}q", f.read(8 * ndim)) if ndim else ()
        return _load_sparse(f, stype, shape)
    if magic == _ND_MAGIC_V2:
        (stype,) = struct.unpack("<i", f.read(4))
        if stype != -1:
            # upstream v2 SPARSE layout (aux types/shapes before data)
            # is not byte-verifiable against the empty reference mount —
            # reject loudly instead of misparsing; fork-written sparse
            # records use _ND_MAGIC_SPARSE
            raise MXNetError(f"sparse storage type {stype} under the "
                             "upstream v2 magic is not supported")
        (ndim,) = struct.unpack("<I", f.read(4))
        shape = struct.unpack(f"<{ndim}q", f.read(8 * ndim)) if ndim else ()
    elif magic == _ND_MAGIC_V1:
        (ndim,) = struct.unpack("<I", f.read(4))
        shape = struct.unpack(f"<{ndim}I", f.read(4 * ndim)) if ndim else ()
    else:
        raise MXNetError(f"invalid NDArray magic {magic:#x}")
    struct.unpack("<ii", f.read(8))  # context (ignored; load to default)
    (flag,) = struct.unpack("<i", f.read(4))
    n = 1
    for s in shape:
        n *= s
    return _from_jax(_read_flag_values(f, flag, n, shape))


def _load_sparse(f, stype, shape):
    from .sparse import CSRNDArray, RowSparseNDArray

    struct.unpack("<ii", f.read(8))  # context
    (flag,) = struct.unpack("<i", f.read(4))
    if stype == _STYPE_ROW_SPARSE:
        cols = 1
        for s in shape[1:]:
            cols *= s
        (k,) = struct.unpack("<Q", f.read(8))
        idx = _np.frombuffer(f.read(8 * k), dtype=_np.int64)
        vals = _read_flag_values(f, flag, k * cols,
                                 (k,) + tuple(shape[1:]))
        return RowSparseNDArray(idx, vals, shape)
    (nnz,) = struct.unpack("<Q", f.read(8))
    indices = _np.frombuffer(f.read(8 * nnz), dtype=_np.int64)
    (nptr,) = struct.unpack("<Q", f.read(8))
    indptr = _np.frombuffer(f.read(8 * nptr), dtype=_np.int64)
    data = _read_flag_values(f, flag, nnz, (nnz,))
    return CSRNDArray(data, indices, indptr, shape)


def save(fname: str, data) -> None:
    """Save a list or str->NDArray dict (``.params`` format)."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    else:
        names, arrays = [], list(data)
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            _save_ndarray(f, a)
        f.write(struct.pack("<Q", len(names)))
        for nme in names:
            b = nme.encode("utf-8")
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load(fname: str):
    """Load a ``.params`` file → dict (named) or list (unnamed)."""
    with open(fname, "rb") as f:
        magic, _ = struct.unpack("<QQ", f.read(16))
        if magic != _LIST_MAGIC:
            raise MXNetError(f"invalid .params magic {magic:#x}")
        (count,) = struct.unpack("<Q", f.read(8))
        arrays = [_load_ndarray(f) for _ in range(count)]
        (n_names,) = struct.unpack("<Q", f.read(8))
        names = []
        for _ in range(n_names):
            (ln,) = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode("utf-8"))
    if names:
        return dict(zip(names, arrays))
    return arrays
