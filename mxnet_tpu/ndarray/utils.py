"""NDArray serialization: mx.nd.save / mx.nd.load.

Reference parity: src/ndarray/ndarray.cc NDArray::Save/Load + the
kMXAPINDArrayListMagic container written by MXNDArraySave (src/c_api/c_api.cc).
Format (little-endian), best-effort byte-compatible with the reference's
``.params`` files so upstream model-zoo weights load directly:

  container:  uint64 0x112 (kMXAPINDArrayListMagic), uint64 reserved=0,
              uint64 n_arrays, n_arrays × NDArray records,
              uint64 n_names, n_names × (uint64 len, bytes) names
  ndarray:    uint32 0xF993fac9 (NDARRAY_V2_MAGIC), int32 stype (-1 dense),
              uint32 ndim, int64[ndim] shape, int32 dev_type, int32 dev_id,
              int32 type_flag, raw data bytes

NOTE: the reference mount was empty at survey time (SURVEY.md preamble);
field order follows upstream apache/incubator-mxnet 1.x and must be
re-verified against the fork if the mount is populated.
"""

from __future__ import annotations

import struct

import numpy as _np

from ..base import MXNetError
from .ndarray import NDArray, _from_jax

_LIST_MAGIC = 0x112
_ND_MAGIC_V2 = 0xF993FAC9
_ND_MAGIC_V1 = 0xF993FAC8

# reference type flags (mshadow/base.h)
_TYPE_FLAGS = {
    _np.dtype("float32"): 0, _np.dtype("float64"): 1,
    _np.dtype("float16"): 2, _np.dtype("uint8"): 3,
    _np.dtype("int32"): 4, _np.dtype("int8"): 5, _np.dtype("int64"): 6,
}
_FLAG_TYPES = {v: k for k, v in _TYPE_FLAGS.items()}
_BF16_FLAG = 12  # extension flag for bfloat16 (not in 1.x reference)


def _save_ndarray(f, arr: NDArray):
    a = arr.asnumpy()
    dt = a.dtype
    if dt.name == "bfloat16":
        flag = _BF16_FLAG
        raw = a.view(_np.uint16)
    elif dt == _np.dtype("bool"):
        a = a.astype("uint8")
        flag = _TYPE_FLAGS[a.dtype]
        raw = a
    else:
        if dt not in _TYPE_FLAGS:
            a = a.astype("float32")
            dt = a.dtype
        flag = _TYPE_FLAGS[dt]
        raw = a
    f.write(struct.pack("<I", _ND_MAGIC_V2))
    f.write(struct.pack("<i", -1))  # dense storage type
    f.write(struct.pack("<I", a.ndim))
    f.write(struct.pack(f"<{a.ndim}q", *a.shape))
    f.write(struct.pack("<ii", 1, 0))  # context: cpu(0) — ctx stripped on save
    f.write(struct.pack("<i", flag))
    f.write(raw.tobytes())


def _load_ndarray(f) -> NDArray:
    import jax.numpy as jnp

    (magic,) = struct.unpack("<I", f.read(4))
    if magic == _ND_MAGIC_V2:
        (stype,) = struct.unpack("<i", f.read(4))
        if stype not in (-1,):
            raise MXNetError(f"sparse storage type {stype} in file not "
                             "supported (dense-only on TPU)")
        (ndim,) = struct.unpack("<I", f.read(4))
        shape = struct.unpack(f"<{ndim}q", f.read(8 * ndim)) if ndim else ()
    elif magic == _ND_MAGIC_V1:
        (ndim,) = struct.unpack("<I", f.read(4))
        shape = struct.unpack(f"<{ndim}I", f.read(4 * ndim)) if ndim else ()
    else:
        raise MXNetError(f"invalid NDArray magic {magic:#x}")
    struct.unpack("<ii", f.read(8))  # context (ignored; load to default)
    (flag,) = struct.unpack("<i", f.read(4))
    n = 1
    for s in shape:
        n *= s
    if flag == _BF16_FLAG:
        raw = _np.frombuffer(f.read(2 * n), dtype=_np.uint16)
        arr = jnp.asarray(raw).view(jnp.bfloat16).reshape(shape)
    else:
        dt = _FLAG_TYPES[flag]
        raw = _np.frombuffer(f.read(dt.itemsize * n), dtype=dt)
        arr = jnp.asarray(raw.reshape(shape))
    return _from_jax(arr)


def save(fname: str, data) -> None:
    """Save a list or str->NDArray dict (``.params`` format)."""
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    else:
        names, arrays = [], list(data)
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQ", _LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for a in arrays:
            _save_ndarray(f, a)
        f.write(struct.pack("<Q", len(names)))
        for nme in names:
            b = nme.encode("utf-8")
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load(fname: str):
    """Load a ``.params`` file → dict (named) or list (unnamed)."""
    with open(fname, "rb") as f:
        magic, _ = struct.unpack("<QQ", f.read(16))
        if magic != _LIST_MAGIC:
            raise MXNetError(f"invalid .params magic {magic:#x}")
        (count,) = struct.unpack("<Q", f.read(8))
        arrays = [_load_ndarray(f) for _ in range(count)]
        (n_names,) = struct.unpack("<Q", f.read(8))
        names = []
        for _ in range(n_names):
            (ln,) = struct.unpack("<Q", f.read(8))
            names.append(f.read(ln).decode("utf-8"))
    if names:
        return dict(zip(names, arrays))
    return arrays
