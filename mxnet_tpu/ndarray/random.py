"""mx.nd.random — sampling namespace (python/mxnet/ndarray/random.py)."""

from __future__ import annotations

from ..ops import registry as _registry
from . import register as _register


def _alias(public, opname):
    opdef = _registry.get(opname)

    def f(*args, **kwargs):
        return _register.invoke(opdef, args, kwargs)

    f.__name__ = public
    return f


uniform = _alias("uniform", "random_uniform")
normal = _alias("normal", "random_normal")
randn = lambda *shape, **kw: normal(shape=shape, **kw)  # noqa: E731
gamma = _alias("gamma", "random_gamma")
exponential = _alias("exponential", "random_exponential")
poisson = _alias("poisson", "random_poisson")
negative_binomial = _alias("negative_binomial", "random_negative_binomial")
generalized_negative_binomial = _alias(
    "generalized_negative_binomial", "random_generalized_negative_binomial")
randint = _alias("randint", "random_randint")
multinomial = _alias("multinomial", "sample_multinomial")
shuffle = _alias("shuffle", "shuffle")
bernoulli = _alias("bernoulli", "random_bernoulli")
