"""mx.nd.contrib — contrib namespace (python/mxnet/ndarray/contrib.py).

Control flow (foreach/while_loop/cond) + misc contrib ops.  Detection ops
(box_nms, ROIAlign, MultiBox*) are registered in mxnet_tpu.ops.contrib_ops
and surface here via the generated wrappers.
"""

from __future__ import annotations

from ..ops.control_flow import foreach, while_loop, cond  # noqa: F401
from ..ops import registry as _registry
from . import register as _register


def _expose(namespace=None):
    ns = namespace if namespace is not None else globals()
    for name, opdef in _registry.all_ops().items():
        if name.startswith("_contrib_"):
            public = name[len("_contrib_"):]
        elif name.startswith("contrib_"):
            public = name[len("contrib_"):]
        else:
            continue
        ns.setdefault(public, _register._make_wrapper(opdef))


def div_sqrt_dim(data):
    return data / (data.shape[-1] ** 0.5)


def arange_like(data, start=0.0, step=1.0, repeat=1, axis=None):
    from . import arange

    if axis is None:
        n = data.size
    else:
        n = data.shape[axis]
    return arange(start, start + step * n, step, repeat=repeat)


_expose()
