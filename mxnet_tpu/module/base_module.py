"""BaseModule: the legacy high-level train/predict loop.

Reference parity: python/mxnet/module/base_module.py — fit / score /
predict / forward_backward over an underlying bound executor.
"""

from __future__ import annotations

import logging
import time

from .. import metric as metric_mod
from ..model import BatchEndParam


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- abstract surface ------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError()

    def backward(self, out_grads=None):
        raise NotImplementedError()

    def update(self):
        raise NotImplementedError()

    def get_outputs(self):
        raise NotImplementedError()

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        raise NotImplementedError()

    def bind(self, *args, **kwargs):
        raise NotImplementedError()

    def init_params(self, *args, **kwargs):
        raise NotImplementedError()

    def init_optimizer(self, *args, **kwargs):
        raise NotImplementedError()

    def prepare(self, data_batch, sparse_row_id_fn=None):
        """Reference: BaseModule.prepare — before forward, pull the
        row-sparse parameter rows the batch will touch from the dist
        kvstore.  In this rebuild Module executors bind DENSE parameters
        (sparse training is the gluon path: Embedding(sparse_grad=True)
        + Trainer — see docs/sparse.md), so there are no row_sparse
        module params to pull; the hook is honored for API parity and
        ``sparse_row_id_fn`` is still invoked (its cost model — knowing
        the touched rows — may matter to callers)."""
        if sparse_row_id_fn is not None:
            sparse_row_id_fn(data_batch)

    def install_monitor(self, mon):
        """Attach a mx.monitor.Monitor to this module's executor(s)
        (reference: BaseModule.install_monitor)."""
        exe = getattr(self, "_exec", None)
        if exe is None:
            raise RuntimeError("install_monitor requires a bound module")
        mon.install(exe)

    # -- training loop ---------------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """Reference: BaseModule.score."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)
        eval_metric.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric,
                                      locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(param)
        if score_end_callback:
            param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                  eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(param)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """Reference: BaseModule.predict."""
        import numpy as np

        from .. import ndarray as nd

        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            outputs = self.get_outputs()
            output_list.append([o.asnumpy() for o in outputs])
        if not output_list:
            return []
        if merge_batches:
            num_outputs = len(output_list[0])
            merged = [nd.array(np.concatenate(
                [out[i] for out in output_list]))
                for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return merged[0]
            return merged
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The classic training loop (reference: BaseModule.fit)."""
        from .. import initializer as init_mod

        assert num_epoch is not None, "please specify number of epochs"
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if initializer is None:
            initializer = init_mod.Uniform(0.01)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=dict(optimizer_params))
        if monitor is not None:
            self.install_monitor(monitor)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, metric_mod.EvalMetric):
            eval_metric = metric_mod.create(eval_metric)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            train_data.reset()
            for data_batch in train_data:
                if monitor is not None:
                    monitor.tic()
                self.prepare(data_batch,
                             sparse_row_id_fn=sparse_row_id_fn)
                self.forward_backward(data_batch)
                # toc BEFORE update(): the optimizer mutates arg_dict in
                # place, and Monitor.toc re-evaluates from those arrays —
                # stats must reflect the weights the forward actually used
                if monitor is not None:
                    monitor.toc_print()
                self.update()
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=eval_metric,
                                          locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(param)
                nbatch += 1
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            toc = time.time()
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch, toc - tic)
            if epoch_end_callback is not None:
                arg_params, aux_params = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_params, aux_params)
            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)

    @property
    def symbol(self):
        return self._symbol


def _as_list(obj):
    if isinstance(obj, (list, tuple)):
        return obj
    return [obj]
