"""Module: symbolic training on a bound executor.

Reference parity: python/mxnet/module/module.py — bind/init_params/
init_optimizer/forward/backward/update/get_params/save_checkpoint.

TPU-first: one executor per module (the whole graph is one XLA program).
The reference's DataParallelExecutorGroup (one executor per GPU +
kvstore reduce) is superseded by mesh sharding — run Module inside
``parallel.make_mesh(dp=N)`` shardings, or use parallel.ShardedTrainer for
the compiled multi-chip step.
"""

from __future__ import annotations

import logging

from .. import initializer as init_mod
from .. import optimizer as opt_mod
from ..base import MXNetError
from ..model import load_checkpoint, save_checkpoint
from ..ndarray.ndarray import NDArray, _from_jax
from .base_module import BaseModule


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        self._symbol = symbol
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        self._param_names = [n for n in arg_names
                             if n not in self._data_names
                             and n not in self._label_names]
        self._aux_names = symbol.list_auxiliary_states()
        self._exec = None
        self._optimizer = None
        self._updater = None
        self._kvstore = None

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        """Reference: Module.bind → GraphExecutor::Init."""
        if self.binded and not force_rebind:
            return
        shapes = {}
        for desc in data_shapes:
            name, shape = desc[0], desc[1]
            shapes[name] = shape
        if label_shapes:
            for desc in label_shapes:
                shapes[desc[0]] = desc[1]
        self._batch_size = data_shapes[0][1][0]
        req = {}
        for n in self._symbol.list_arguments():
            if n in self._data_names or n in self._label_names or \
                    n in self._fixed_param_names:
                req[n] = "null"
            else:
                req[n] = grad_req
        self._exec = self._symbol.simple_bind(grad_req=req, **shapes)
        self.binded = True
        self.for_training = for_training

    def init_params(self, initializer=None, arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        """Reference: Module.init_params."""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        if initializer is None:
            initializer = init_mod.Uniform(0.01)
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params and name in arg_params:
                arr._set_data(arg_params[name]._data)
            else:
                if arg_params and not allow_missing and arg_params:
                    raise MXNetError(f"parameter {name} missing")
                initializer(init_mod.InitDesc(name), arr)
        self.params_initialized = True

    def get_params(self):
        arg_params = {n: self._exec.arg_dict[n].copy()
                      for n in self._param_names}
        aux_params = {n: v.copy() for n, v in self._exec.aux_dict.items()}
        return arg_params, aux_params

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Reference: Module.init_optimizer (+ kvstore wiring)."""
        if self.optimizer_initialized and not force_init:
            return
        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            if "rescale_grad" not in optimizer_params:
                # reference behavior: normalize summed grads by batch size
                optimizer_params["rescale_grad"] = \
                    1.0 / getattr(self, "_batch_size", 1)
            optimizer = opt_mod.create(
                optimizer, param_idx2name=idx2name, sym=self._symbol,
                **optimizer_params)
        self._optimizer = optimizer
        self._updater = opt_mod.get_updater(optimizer)
        if kvstore:
            from .. import kvstore as kv_mod

            kv = kv_mod.create(kvstore) if isinstance(kvstore, str) \
                else kvstore
            if kv.num_workers > 1 or kv.type.startswith("dist"):
                self._kvstore = kv
                for i, name in enumerate(self._param_names):
                    kv.init(i, self._exec.arg_dict[name])
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        feed = dict(zip(self._data_names, data_batch.data))
        if data_batch.label is not None and self._label_names:
            feed.update(zip(self._label_names, data_batch.label))
        self._exec.forward(is_train=is_train, **feed)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def update(self):
        """Reference: Module.update — kvstore reduce + fused updater."""
        assert self.optimizer_initialized
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            weight = self._exec.arg_dict[name]
            if self._kvstore is not None:
                self._kvstore.pushpull(i, grad, out=grad)
            self._updater(i, grad, weight)

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self._exec.outputs)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False,
                        remove_amp_cast=True):
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params,
                        aux_params)
        if save_optimizer_states and self._updater is not None:
            with open(f"{prefix}-{epoch:04d}.states", "wb") as f:
                f.write(self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._preloaded = (arg_params, aux_params)
        mod._preloaded_states = f"{prefix}-{epoch:04d}.states" \
            if load_optimizer_states else None
        return mod


class BucketingModule(BaseModule):
    """Per-bucket Modules sharing parameters (reference:
    python/mxnet/module/bucketing_module.py — variable-length batching).

    On TPU, per-bucket graphs are per-shape XLA programs: binding a bucket
    is just another jit signature, so this stays cheap.
    """

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, **kwargs):
        super().__init__(logger=logger)
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._kwargs = kwargs

    @property
    def default_bucket_key(self):
        return self._default_bucket_key

    def _get_module(self, bucket_key):
        if bucket_key not in self._buckets:
            sym, data_names, label_names = self._sym_gen(bucket_key)
            self._buckets[bucket_key] = Module(
                sym, data_names=data_names, label_names=label_names,
                **self._kwargs)
        return self._buckets[bucket_key]

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        mod = self._get_module(bucket_key)
        if not mod.binded:
            mod.bind(data_shapes, label_shapes,
                     for_training=self.for_training)
            if self._curr_module is not None and \
                    self._curr_module.params_initialized:
                arg, aux = self._curr_module.get_params()
                mod.init_params(arg_params=arg, aux_params=aux,
                                allow_missing=False, force_init=True)
            if getattr(self, "_monitor", None) is not None:
                mod.install_monitor(self._monitor)
        self._curr_module = mod
        self._curr_bucket_key = bucket_key

    def install_monitor(self, mon):
        """Install on every bound bucket, and on buckets bound later
        (reference: BucketingModule.install_monitor)."""
        self._monitor = mon
        for mod in self._buckets.values():
            if mod.binded:
                mod.install_monitor(mon)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             **kwargs):
        self.for_training = for_training
        self.switch_bucket(self._default_bucket_key, data_shapes,
                           label_shapes)
        self.binded = True

    def init_params(self, **kwargs):
        self._curr_module.init_params(**kwargs)
        self.params_initialized = True

    def init_optimizer(self, **kwargs):
        self._opt_kwargs = kwargs
        self._curr_module.init_optimizer(**kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = data_batch.bucket_key if data_batch.bucket_key is not None \
            else self._default_bucket_key
        if key != self._curr_bucket_key:
            prev = self._curr_module
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)
            if not self._curr_module.params_initialized and prev:
                arg, aux = prev.get_params()
                self._curr_module.init_params(arg_params=arg,
                                              aux_params=aux,
                                              force_init=True)
            if not self._curr_module.optimizer_initialized and \
                    self.optimizer_initialized:
                self._curr_module.init_optimizer(**self._opt_kwargs)
        self._curr_module.forward(data_batch, is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels, pre_sliced)

    def get_params(self):
        return self._curr_module.get_params()

    @property
    def symbol(self):
        return self._curr_module.symbol if self._curr_module else None
