"""Custom operators: mx.operator.CustomOp / CustomOpProp / register.

Reference parity: src/operator/custom/custom.cc + python/mxnet/operator.py —
user-defined ops written as Python callbacks, invoked via
``mx.nd.Custom(..., op_type=name)``.

TPU-first note: a CustomOp's forward/backward run eagerly on NDArrays (host
roundtrip), exactly like the reference's python-callback path.  Performance-
critical custom kernels should instead be pure-JAX/Pallas functions
registered with ``mxnet_tpu.ops.register`` — that is this framework's analog
of writing a C++/CUDA operator.
"""

from __future__ import annotations

from .base import MXNetError, _Registry

_custom_registry = _Registry("custom_op")


class CustomOp:
    """Base for custom op implementations (forward/backward on NDArrays)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        raw = src._data if hasattr(src, "_data") else src
        if req in ("write", "inplace", None):
            dst._set_data(raw)
        elif req == "add":
            dst._set_data(dst._data + raw)
        # req == 'null': no-op


class CustomOpProp:
    """Shape/type/arg metadata for a custom op (reference: CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def create_operator(self, ctx, shapes, dtypes):
        raise NotImplementedError

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


def register(reg_name):
    """Decorator: @mx.operator.register("myop") on a CustomOpProp subclass."""

    def _do(prop_cls):
        _custom_registry.register(prop_cls, name=reg_name)
        return prop_cls

    return _do


def get(name):
    return _custom_registry.get(name)


def _invoke_custom(op_type, data, kwargs):
    """Backend for the registered 'Custom' op (mxnet_tpu/ops/nn.py)."""
    from . import autograd
    from .ndarray import _from_jax
    from .ndarray.ndarray import NDArray

    if op_type is None or op_type not in _custom_registry:
        raise MXNetError(
            f"Custom op_type {op_type!r} is not registered; use "
            "@mx.operator.register(name) on a CustomOpProp subclass")
    prop_cls = _custom_registry.get(op_type)
    prop = prop_cls(**kwargs) if kwargs else prop_cls()

    inputs = [d if isinstance(d, NDArray) else _from_jax(d) for d in data]
    in_shapes = [list(i.shape) for i in inputs]
    in_shapes, out_shapes, aux_shapes = prop.infer_shape(in_shapes)
    op = prop.create_operator(None, in_shapes, [i.dtype for i in inputs])

    from . import nd

    out_data = [nd.zeros(tuple(s)) for s in out_shapes]
    aux = [nd.zeros(tuple(s)) for s in aux_shapes]

    class _Fn(autograd.Function):
        def forward(self, *ins):
            op.forward(autograd.is_training(), ["write"] * len(out_data),
                       list(ins), out_data, aux)
            self.save_for_backward(list(ins), out_data)
            return tuple(out_data) if len(out_data) > 1 else out_data[0]

        def backward(self, *ograds):
            ins, outs = self._saved
            in_grad = [nd.zeros(i.shape) for i in ins]
            op.backward(["write"] * len(in_grad), list(ograds), ins, outs,
                        in_grad, aux)
            return tuple(in_grad) if len(in_grad) > 1 else in_grad[0]

    return _Fn()(*inputs)
