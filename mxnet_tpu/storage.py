"""Device-memory management surface.

Reference parity: src/storage/ (pooled_storage_manager.h) and the
`MXNET_GPU_MEM_POOL_*` env plane.  On TPU the allocator IS the PJRT
client (best-fit + BFC arena inside libtpu), so the reference's
hand-rolled pool is replaced by knobs that configure that client plus
introspection over its live statistics:

- `MXNET_TPU_MEM_FRACTION`   → XLA_PYTHON_CLIENT_MEM_FRACTION
  (reference analog: MXNET_GPU_MEM_POOL_RESERVE, inverted — fraction to
  USE rather than reserve)
- `MXNET_TPU_PREALLOCATE`    → XLA_PYTHON_CLIENT_PREALLOCATE
  (reference analog: pooled vs naive storage manager — preallocating is
  the pooled behavior)
- `MXNET_TPU_ALLOCATOR`      → XLA_PYTHON_CLIENT_ALLOCATOR
  (`platform` = naive per-buffer alloc, like MXNET_GPU_MEM_POOL_TYPE=Naive)

`apply_env()` runs at package import, BEFORE jax initializes, so the
knobs take effect the same way the reference reads its pool env at
Storage::Get() construction.
"""

from __future__ import annotations

import os

_ENV_MAP = [
    ("MXNET_TPU_MEM_FRACTION", "XLA_PYTHON_CLIENT_MEM_FRACTION"),
    ("MXNET_TPU_PREALLOCATE", "XLA_PYTHON_CLIENT_PREALLOCATE"),
    ("MXNET_TPU_ALLOCATOR", "XLA_PYTHON_CLIENT_ALLOCATOR"),
]


def apply_env():
    """Map MXNET_* memory knobs onto the XLA client env (no-op for
    already-set XLA vars: explicit XLA config wins)."""
    for src, dst in _ENV_MAP:
        if src in os.environ and dst not in os.environ:
            os.environ[dst] = os.environ[src]


def memory_info(ctx=None):
    """(free_bytes, total_bytes) for a device — reference:
    mx.context.gpu_memory_info (MXGetGPUMemoryInformation64).  Returns
    (None, None) when the backend exposes no stats (CPU)."""
    import jax

    if ctx is not None and hasattr(ctx, "_jax_device"):
        dev = ctx._jax_device()
    else:
        idx = getattr(ctx, "device_id", 0) if ctx is not None else 0
        devs = jax.local_devices()
        dev = devs[min(idx, len(devs) - 1)]
    stats = None
    try:
        stats = dev.memory_stats()
    except Exception:
        return (None, None)
    if not stats:
        return (None, None)
    total = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
    used = stats.get("bytes_in_use", 0)
    if total is None:
        return (None, None)
    return (int(total) - int(used), int(total))


def memory_summary(ctx=None):
    """Human-readable allocator statistics (reference analog: the
    storage profiler dump)."""
    free, total = memory_info(ctx)
    if total is None:
        return "device exposes no memory statistics"
    used = total - free
    return (f"used {used / 2**20:.1f} MiB / {total / 2**20:.1f} MiB "
            f"({100.0 * used / total:.1f}%)")
