"""Grouped (multi-tensor) optimizer stepping for the imperative Trainer.

Reference parity: the `multi_sgd_update` / `multi_mp_sgd_update` /
`multi_lamb` family (src/operator/optimizer_op.cc ≥1.6) plus Gluon's
`Trainer` aggregation (`MXNET_OPTIMIZER_AGGREGATION_SIZE`): instead of one
kernel launch per parameter, whole groups of parameters step in a single
fused call.

TPU-first design: `GroupedUpdater` partitions a Trainer's parameters into
groups keyed by (update kernel, static hyper-params, dtype) and applies
each group in ONE cached `jax.jit` program — pytrees of weights, grads and
states in, pytrees out, with weights and states donated so XLA updates
in place.  Per-step scalars (lr, wd, rescale_grad and the host-folded
step-count coefficients) enter as traced f32/f16 scalars cast to the
group dtype on the host, which keeps LR schedules from retracing AND
keeps the arithmetic bitwise-identical to the eager per-parameter loop
(a Python float in eager mode is weakly typed and rounds to the array
dtype in one step — exactly what the host-side cast does).

Anything the grouped kernels cannot express bitwise-identically — the
inline-eager optimizers (Nadam, Adamax, DCASGD, SGLD, Test), row-sparse
gradients, multi-precision fp16 master weights — falls back to the legacy
`Updater` per-parameter path, so numerics never change silently.
"""

from __future__ import annotations

import math
import os
import warnings

import numpy as _np

from ..ndarray.ndarray import NDArray
from ..ops import optimizer_op as _op
from . import optimizer as _optmod

# CPU/older backends cannot honor buffer donation; jax warns per call.
# The fallback (a copy) is correct, so the warning is pure noise here.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


def fused_step_enabled() -> bool:
    """MXTPU_FUSED_STEP gate (default on); 0/false/off restores the
    legacy per-parameter loop."""
    return os.environ.get("MXTPU_FUSED_STEP", "1").lower() \
        not in ("0", "false", "off", "")


def group_max_items() -> int:
    """MXTPU_GROUP_MAX_ITEMS: cap on params fused into one optimizer
    group (0 = unlimited).  An autotune knob (autotune/space.py):
    re-read on every `plan_items` call, so a mid-run change re-plans —
    and, because the plan signature keys the capture cache, re-captures
    — the next step.  Splitting is bitwise-neutral: the group kernel
    loops per item, so chunk boundaries change fusion, never math."""
    try:
        return max(0, int(os.environ.get("MXTPU_GROUP_MAX_ITEMS", "0")))
    except ValueError:
        return 0


# -- dispatch accounting (regression-tested: one jit call per group/step) ------

_DISPATCH_COUNT = 0


def dispatch_count() -> int:
    """Number of grouped optimizer-update XLA dispatches since the last
    reset — exactly one per (kernel, static hyper-params, dtype) group
    per step."""
    return _DISPATCH_COUNT


def reset_dispatch_count() -> None:
    global _DISPATCH_COUNT
    _DISPATCH_COUNT = 0


# -- per-optimizer grouping plans ----------------------------------------------
#
# A plan maps one (optimizer, index, weight, state) item to
# (kernel, static_kwargs, state_ndarrays, dyn_fn).  `static_kwargs` are
# Python constants baked into the trace (identical to the eager call's
# keyword constants); `dyn_fn(opt, index)` runs AFTER the update count is
# bumped and returns the per-step host scalars, matching the exact float64
# expressions the eager optimizers compute before entering their kernels.


def _cg(opt):
    # pure kernels treat clip_gradient<0 as "no clipping", same as the
    # eager path omitting the kwarg
    return -1.0 if opt.clip_gradient is None else float(opt.clip_gradient)


def _dyn_lrwd(opt, index):
    return {"lr": opt._get_lr(index), "wd": opt._get_wd(index),
            "rescale_grad": opt.rescale_grad}


def _dyn_wd(opt, index):
    return {"wd": opt._get_wd(index), "rescale_grad": opt.rescale_grad}


def _dyn_adam(opt, index):
    d = _dyn_lrwd(opt, index)
    t = opt._index_update_count[index]
    coef1 = 1.0 - opt.beta1 ** t
    coef2 = 1.0 - opt.beta2 ** t
    d["lr"] = d["lr"] * (math.sqrt(coef2) / coef1)
    return d


def _dyn_lamb(opt, index):
    d = _dyn_lrwd(opt, index)
    t = opt._index_update_count[index]
    if opt.bias_correction:
        d["denom1"] = 1.0 - opt.beta1 ** t
        d["denom2"] = 1.0 - opt.beta2 ** t
    else:
        # x / 1.0 is an IEEE identity → bitwise-equal to the
        # uncorrected eager branch
        d["denom1"] = 1.0
        d["denom2"] = 1.0
    return d


def _dyn_ftml(opt, index):
    lr = opt._get_lr(index)
    t = opt._index_update_count[index]
    return {"c_over_lr": (1.0 - opt.beta1 ** t) / lr,
            "coef2": 1.0 - opt.beta2 ** t,
            "wd": opt._get_wd(index),
            "rescale_grad": opt.rescale_grad}


def _plan_sgd(o, i, w, state):
    if state is not None:
        return (_op.sgd_mom_update_pure,
                {"momentum": o.momentum, "clip_gradient": _cg(o)},
                [state], _dyn_lrwd)
    return (_op.sgd_update_pure, {"clip_gradient": _cg(o)}, [], _dyn_lrwd)


def _plan_nag(o, i, w, state):
    if state is not None:
        return (_op.nag_mom_update_pure,
                {"momentum": o.momentum, "clip_gradient": _cg(o)},
                [state], _dyn_lrwd)
    return (_op.sgd_update_pure, {"clip_gradient": _cg(o)}, [], _dyn_lrwd)


def _plan_adam(o, i, w, state):
    return (_op.adam_update_pure,
            {"beta1": o.beta1, "beta2": o.beta2, "epsilon": o.epsilon,
             "clip_gradient": _cg(o)},
            list(state), _dyn_adam)


def _plan_adamw(o, i, w, state):
    return (_op.adamw_update_pure,
            {"beta1": o.beta1, "beta2": o.beta2, "epsilon": o.epsilon,
             "clip_gradient": _cg(o)},
            list(state), _dyn_adam)


def _plan_rmsprop(o, i, w, state):
    cw = float(o.clip_weights) if o.clip_weights else -1.0
    if o.centered:
        return (_op.rmspropalex_update_pure,
                {"gamma1": o.gamma1, "gamma2": o.gamma2,
                 "epsilon": o.epsilon, "clip_gradient": _cg(o),
                 "clip_weights": cw},
                list(state), _dyn_lrwd)
    return (_op.rmsprop_update_pure,
            {"gamma1": o.gamma1, "epsilon": o.epsilon,
             "clip_gradient": _cg(o), "clip_weights": cw},
            list(state), _dyn_lrwd)


def _plan_adagrad(o, i, w, state):
    return (_op.adagrad_update_pure,
            {"epsilon": o.float_stable_eps, "clip_gradient": _cg(o)},
            [state], _dyn_lrwd)


def _plan_adadelta(o, i, w, state):
    return (_op.adadelta_update_pure,
            {"rho": o.rho, "epsilon": o.epsilon, "clip_gradient": _cg(o)},
            list(state), _dyn_wd)


def _plan_ftrl(o, i, w, state):
    return (_op.ftrl_update_pure,
            {"lamda1": o.lamda1, "beta": o.beta, "clip_gradient": _cg(o)},
            list(state), _dyn_lrwd)


def _plan_signum(o, i, w, state):
    if state is not None:
        return (_op.signum_update_pure,
                {"momentum": o.momentum, "wd_lh": o.wd_lh,
                 "clip_gradient": _cg(o)},
                [state], _dyn_lrwd)
    return (_op.signsgd_update_pure, {"clip_gradient": _cg(o)}, [],
            _dyn_lrwd)


def _plan_lamb(o, i, w, state):
    lb = -1.0 if o.lower_bound is None else float(o.lower_bound)
    ub = -1.0 if o.upper_bound is None else float(o.upper_bound)
    return (_op.lamb_fused_update_pure,
            {"beta1": o.beta1, "beta2": o.beta2, "epsilon": o.epsilon,
             "clip_gradient": _cg(o), "lower_bound": lb, "upper_bound": ub},
            list(state), _dyn_lamb)


def _plan_lars(o, i, w, state):
    # 1-D params (biases, norm scales) take the plain momentum step —
    # the optimizer's own skip list
    if len(w.shape) <= 1:
        return (_op.sgd_mom_update_pure,
                {"momentum": o.momentum, "clip_gradient": _cg(o)},
                [state], _dyn_lrwd)
    return (_op.lars_update_pure,
            {"momentum": o.momentum, "eta": o.eta, "epsilon": o.epsilon,
             "clip_gradient": _cg(o)},
            [state], _dyn_lrwd)


def _plan_ftml(o, i, w, state):
    return (_op.ftml_fused_update_pure,
            {"beta1": o.beta1, "beta2": o.beta2, "epsilon": o.epsilon,
             "clip_grad": _cg(o)},
            list(state), _dyn_ftml)


# -- row-sparse (lazy-update) kernel wrappers ----------------------------------

_SPARSE_KERNELS = {}


def sparse_row_kernel(kernel):
    """Row-sparse lazy-update variant of a dense update kernel.

    The wrapped kernel sees ``grad`` as a ``(row_ids, row_values)`` pair:
    it gathers the touched rows of the weight and every state, runs the
    SAME elementwise dense kernel on just those rows (the exact call
    `Optimizer._apply`'s eager sparse branch makes, including the
    values-to-weight-dtype cast), and scatters the results back with
    ``.at[ids].set``.  Untouched rows never enter the arithmetic, so
    they stay bit-identical — lazy-update semantics.

    Out-of-range ids are the captured step's padding convention
    (sentinel id == vocab): the gather may fill those rows with
    garbage, but JAX scatter DROPS out-of-bounds updates, so padded
    rows write nothing.  One wrapper per dense kernel is cached so the
    group key — ``(kernel, static_items, dtype)`` — stays stable across
    plans and capture signatures."""
    fn = _SPARSE_KERNELS.get(kernel)
    if fn is None:
        import jax.numpy as jnp

        def row_step(weight, grad, *states, **kw):
            ids, vals = grad
            w_rows = jnp.take(weight, ids, axis=0)
            s_rows = [jnp.take(s, ids, axis=0) for s in states]
            res = kernel(w_rows, vals.astype(w_rows.dtype), *s_rows,
                         **kw)
            return (weight.at[ids].set(res[0]),
                    *[s.at[ids].set(r) for s, r in zip(states,
                                                       res[1:])])

        row_step.__name__ = "row_sparse_" \
            + getattr(kernel, "__name__", "kernel")
        _SPARSE_KERNELS[kernel] = fn = row_step
    return fn


def _sparse_groupable(opt, weight, grad):
    """Row-sparse items the grouped row kernel reproduces bitwise
    against the eager sparse oracle: SGD/Adam lazy-update on a dense
    float weight.  Everything else (other optimizers, lazy_update=False
    densification, fp16 master weights) keeps the legacy per-parameter
    path."""
    from ..ndarray.sparse import RowSparseNDArray

    if not isinstance(grad, RowSparseNDArray) \
            or isinstance(weight, RowSparseNDArray):
        return False
    if type(opt) not in (_optmod.SGD, _optmod.Adam):
        return False
    if not getattr(opt, "lazy_update", True):
        return False
    import jax.numpy as jnp

    w_raw = _raw(weight)
    if not jnp.issubdtype(w_raw.dtype, jnp.floating):
        return False
    if opt.multi_precision and w_raw.dtype == _np.float16:
        return False
    return True


# exact-type dispatch: a user SUBCLASS of a registered optimizer may
# override update() arbitrarily, so it must take the legacy loop
_PLANS = {
    _optmod.SGD: _plan_sgd,
    _optmod.NAG: _plan_nag,
    _optmod.Adam: _plan_adam,
    _optmod.AdamW: _plan_adamw,
    _optmod.RMSProp: _plan_rmsprop,
    _optmod.AdaGrad: _plan_adagrad,
    _optmod.AdaDelta: _plan_adadelta,
    _optmod.Ftrl: _plan_ftrl,
    _optmod.Signum: _plan_signum,
    _optmod.LAMB: _plan_lamb,
    _optmod.LARS: _plan_lars,
    # LBSGD only overrides the HOST-side lr warmup (_get_lr), which the
    # dyn scalars already route through — device math is LARS's
    _optmod.LBSGD: _plan_lars,
    _optmod.FTML: _plan_ftml,
}


def _groupable(opt, weight, grad):
    """Items the grouped kernels reproduce bitwise; everything else
    falls back to the per-parameter Updater."""
    from ..ndarray.sparse import RowSparseNDArray

    if isinstance(grad, RowSparseNDArray) \
            or isinstance(weight, RowSparseNDArray):
        return False
    w_raw = weight._data if isinstance(weight, NDArray) else weight
    g_raw = grad._data if isinstance(grad, NDArray) else grad
    import jax.numpy as jnp

    if not jnp.issubdtype(w_raw.dtype, jnp.floating):
        return False
    if w_raw.dtype != g_raw.dtype:
        return False
    if opt.multi_precision and w_raw.dtype == _np.float16:
        return False
    return True


# -- the jitted group program --------------------------------------------------

_GROUP_FN_CACHE = {}


def build_group_step(kernel, static_items, guarded=False, clip=None):
    """Build the PURE (unjitted) group-step function — the single home
    of the fused update math.  `_group_fn` jits it for the eager
    multi-dispatch path; the whole-step capture (`gluon/captured.py`)
    inlines the SAME function into its one donated program, so the two
    paths share every arithmetic decision (clip formula, cond
    branching, kernel unroll order) and stay bitwise-identical.

    Signatures: ``(weights, grads, states, dyn)`` when unguarded and
    unclipped, else ``(weights, grads, states, dyn, health)``; returns
    ``(new_weights, new_states)``.
    """
    import jax
    import jax.numpy as jnp

    static = dict(static_items)

    def run_updates(weights, grads, states, dyn, health):
        coef = None
        if clip is not None:
            norm = jnp.sqrt(health[1])
            coef = jnp.minimum(jnp.float32(1.0),
                               jnp.float32(clip) / (norm + 1e-8))
        new_w, new_s = [], []
        for j in range(len(weights)):
            kw = dict(static)
            for name, col in dyn.items():
                kw[name] = col[j]
            g = grads[j]
            if coef is not None:
                if isinstance(g, tuple):
                    # row-sparse (ids, values): clip scales the values,
                    # ids pass through untouched
                    g = (g[0], g[1] * coef.astype(g[1].dtype))
                else:
                    g = g * coef.astype(g.dtype)
            res = kernel(weights[j], g, *states[j], **kw)
            new_w.append(res[0])
            new_s.append(list(res[1:]))
        return new_w, new_s

    if not guarded and clip is None:
        def group_step(weights, grads, states, dyn):
            return run_updates(weights, grads, states, dyn, None)
    elif not guarded:
        def group_step(weights, grads, states, dyn, health):
            return run_updates(weights, grads, states, dyn, health)
    else:
        def group_step(weights, grads, states, dyn, health):
            ok = (health[0] > 0) & jnp.isfinite(health[1])

            def do_step(ops):
                return run_updates(*ops)

            def skip_step(ops):
                weights, _, states, _, _ = ops
                return list(weights), [list(s) for s in states]

            return jax.lax.cond(
                ok, do_step, skip_step,
                (weights, grads, states, dyn, health))

    return group_step


def _group_fn(kernel, static_items, guarded=False, clip=None):
    """One cached jit program per (kernel, static hyper-params, guard
    config).  Inside the trace the per-item kernels unroll into a single
    XLA module; weights (arg 0) and states (arg 2) are donated so the
    update is in-place on backends that support donation.

    With ``guarded`` the program takes the step's ``(2,)`` health array
    ``[all_finite, global_sq_norm]`` (numerics.grad_health) and branches
    on the health predicate with `jax.lax.cond` — an unhealthy step
    returns the donated inputs bitwise-unchanged, a healthy step runs
    the update math inside the cond's true branch, which XLA compiles as
    its own computation scope so fusion/contraction decisions match the
    unguarded program bitwise (a `jnp.where` over the outputs would pull
    the select INTO the kernel fusion and perturb FMA contraction).
    With ``clip`` (a static float) gradients are pre-scaled by
    ``min(1, clip / (norm + 1e-8))`` — the `gluon.utils.clip_global_norm`
    formula — inside the same program, reusing the already-computed norm.
    """
    key = (kernel, static_items, guarded, clip)
    fn = _GROUP_FN_CACHE.get(key)
    if fn is None:
        import jax

        fn = jax.jit(build_group_step(kernel, static_items,
                                      guarded=guarded, clip=clip),
                     donate_argnums=(0, 2))
        _GROUP_FN_CACHE[key] = fn
    return fn


def _raw(x):
    return x._data if isinstance(x, NDArray) else x


def _place_state_like(state, weight):
    """Lay freshly-created optimizer state over the owning weight's
    NamedSharding (parallel/sharding.py shard_model): same-shaped
    moments shard with the weight so grouped updates run shard-local —
    no gather, no replicated state copy.  Shared by both update paths
    because state creation is shared; a replicated / single-device
    weight leaves the state untouched."""
    from jax.sharding import NamedSharding

    raw_w = _raw(weight)
    sh = getattr(raw_w, "sharding", None)
    if not isinstance(sh, NamedSharding) or sh.mesh.size <= 1:
        return state

    import jax

    def place(s):
        if isinstance(s, (list, tuple)):
            return type(s)(place(v) for v in s)
        if isinstance(s, NDArray) and s.shape == raw_w.shape:
            s._set_data(jax.device_put(s._data, sh))
        return s

    return place(state)


def plan_items(updater, index, grad, weight):
    """Partition ``(index, grad, weight)`` triples into fused groups,
    creating optimizer states on demand through the SAME
    ``create_state_multi_precision`` call as the legacy loop.

    Returns ``(groups, fallback)``: ``groups`` maps
    ``(kernel, static_items, dtype_str)`` to item lists of
    ``(i, w, g, state_nds, dyn_fn)``; ``fallback`` holds the triples
    the kernels cannot express bitwise.  Shared by
    `GroupedUpdater.__call__` and the whole-step capture
    (`gluon/captured.py`), so both agree on what is groupable and on
    the group keying.
    """
    upd = updater
    o = upd.optimizer
    plan = _PLANS.get(type(o))
    groups = {}
    fallback = []
    for i, g, w in zip(index, grad, weight):
        if i not in upd.states:
            upd.states[i] = o.create_state_multi_precision(i, w)
            upd.states_synced[i] = True
            _place_state_like(upd.states[i], w)
        item = None
        if plan is not None and _groupable(o, w, g):
            item = plan(o, i, w, upd.states[i])
        elif plan is not None and _sparse_groupable(o, w, g):
            kernel, static, state_nds, dyn_fn = \
                plan(o, i, w, upd.states[i])
            item = (sparse_row_kernel(kernel), static, state_nds,
                    dyn_fn)
        if item is None:
            fallback.append((i, g, w))
            continue
        kernel, static, state_nds, dyn_fn = item
        static_items = tuple(sorted(static.items()))
        gkey = (kernel, static_items, str(_raw(w).dtype))
        groups.setdefault(gkey, []).append((i, w, g, state_nds, dyn_fn))
    cap = group_max_items()
    if cap > 0:
        # split oversize groups into chunks of <= cap items; the chunk
        # ordinal extends the key (consumers index gkey[0..2], so the
        # extra element is invisible to them)
        split = {}
        for gkey, items in groups.items():
            if len(items) <= cap:
                split[gkey] = items
            else:
                for ci in range(0, len(items), cap):
                    split[gkey + (ci,)] = items[ci:ci + cap]
        groups = split
    return groups, fallback


def dyn_columns(optimizer, items, dtype):
    """Stack one step's per-item host scalars into one ``(n,)`` array
    per scalar name, cast host-side to the group dtype (the rounding a
    weakly-typed Python float would get inside the eager kernel).  Runs
    AFTER the update-count bump; shared by the eager grouped dispatch
    and the captured whole-step program so per-step scalars are
    bit-identical on both paths."""
    dyn_rows = [dyn_fn(optimizer, i) for i, _, _, _, dyn_fn in items]
    return {name: _np.asarray([row[name] for row in dyn_rows], dtype)
            for name in dyn_rows[0]}


class GroupedUpdater:
    """Multi-tensor drop-in for `Updater` on the Trainer's local path.

    Shares the wrapped Updater's `states` dict (and creates states through
    the same `create_state_multi_precision` call), so `save_states` /
    `load_states` and `set_states` round-trip identically whichever path
    ran the steps.
    """

    def __init__(self, updater):
        self._updater = updater

    @property
    def optimizer(self):
        return self._updater.optimizer

    @property
    def states(self):
        return self._updater.states

    def __call__(self, index, grad, weight, guard=None):
        from .. import profiler

        upd = self._updater
        o = upd.optimizer
        if guard is not None and not guard.skip and guard.clip is None:
            guard = None  # nothing for the programs to do with it
        if not isinstance(index, (list, tuple)):
            index, grad, weight = [index], [grad], [weight]
        groups, fallback = plan_items(upd, index, grad, weight)
        # legacy per-parameter loop for whatever the kernels can't express;
        # guarded steps skip these host-side (the guard's one readback —
        # shared with the Trainer's finalize via the StepGuard cache)
        if fallback and guard is not None and guard.skip \
                and not guard.healthy:
            fallback = []
        for i, g, w in fallback:
            upd(i, g, w)
        if not groups:
            return
        # bump every grouped index first (the eager loop bumps one at a
        # time, but num_update is a running max, so the per-item lr/wd
        # read below sees the same value either way)
        for items in groups.values():
            for i, *_ in items:
                o._update_count(i)
        global _DISPATCH_COUNT
        for gkey, items in groups.items():
            kernel, static_items = gkey[0], gkey[1]
            dtype = _raw(items[0][1]).dtype
            from ..ndarray.sparse import RowSparseNDArray

            w_raws = [_raw(w) for _, w, _, _, _ in items]
            # row-sparse grads enter as (ids, values) pairs — NOT the
            # dense ._data view, which would materialize the full table
            g_raws = [(g._rs_indices, g._rs_values)
                      if isinstance(g, RowSparseNDArray) else _raw(g)
                      for _, _, g, _, _ in items]
            s_raws = [[_raw(s) for s in st] for _, _, _, st, _ in items]
            # host-side cast + STACK into one (n,) array per name so the
            # jit pytree carries 1 leaf per scalar name, not n (the
            # per-leaf dispatch cost of n tiny args would eat the
            # fusion win)
            dyn = dyn_columns(o, items, dtype)
            if guard is None:
                fn = _group_fn(kernel, static_items)
                with profiler.annotate("optimizer_update"):
                    new_w, new_s = fn(w_raws, g_raws, s_raws, dyn)
            else:
                fn = _group_fn(kernel, static_items,
                               guarded=guard.skip, clip=guard.clip)
                with profiler.annotate("optimizer_update"):
                    new_w, new_s = fn(w_raws, g_raws, s_raws, dyn,
                                      guard.health)
            _DISPATCH_COUNT += 1
            for (_, w, _, st, _), nw, ns in zip(items, new_w, new_s):
                w._set_data(nw)
                for s_nd, s_new in zip(st, ns):
                    s_nd._set_data(s_new)

    # -- Updater API passthroughs (save/load states) ---------------------------
    def sync_state_context(self, state, context):
        return self._updater.sync_state_context(state, context)

    def set_states(self, states):
        self._updater.set_states(states)

    def get_states(self, dump_optimizer=False):
        return self._updater.get_states(dump_optimizer)
