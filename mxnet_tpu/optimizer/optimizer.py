"""Optimizer registry and built-in optimizers.

Reference parity: python/mxnet/optimizer/optimizer.py — the ``Optimizer``
base (registry, lr/wd multipliers, update counting, multi-precision) and the
built-ins: SGD, NAG, Adam, Adamax, Nadam, RMSProp, AdaGrad, AdaDelta, Ftrl,
Signum, SGLD, DCASGD, LAMB, plus ``Updater``/``get_updater`` (the KVStore
server-side update path).

TPU-first: every update dispatches to a fused pure-JAX op
(ops/optimizer_op.py) — a single XLA elementwise fusion per parameter —
and mutates the weight/state NDArrays by handle swap.
"""

from __future__ import annotations

import logging
import math
import pickle

import numpy as _np

from ..base import MXNetError
from ..ndarray.ndarray import NDArray, _from_jax
from ..ops import optimizer_op as _op
from . import lr_scheduler as lr_scheduler_mod


def _raw(x):
    return x._data if isinstance(x, NDArray) else x


class Optimizer:
    """Base class for optimizers (reference: mx.optimizer.Optimizer)."""

    opt_registry: dict = {}

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        if param_idx2name is None:
            param_idx2name = {}
        if not isinstance(param_idx2name, dict):
            raise ValueError("param_idx2name should be a dict of param "
                             "indexes to names.")
        self.idx2name = param_idx2name.copy()
        # reference: sym carries per-variable __lr_mult__/__wd_mult__
        # attrs (AttrScope / var(lr_mult=...)) that set_lr_mult consults
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) \
            if sym is not None else ()
        self.param_dict = param_dict if param_dict else {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    @staticmethod
    def register(klass):
        assert isinstance(klass, type)
        name = klass.__name__.lower()
        if name in Optimizer.opt_registry:
            logging.warning("WARNING: New optimizer %s.%s is overriding "
                            "existing optimizer %s.%s", klass.__module__,
                            klass.__name__,
                            Optimizer.opt_registry[name].__module__,
                            Optimizer.opt_registry[name].__name__)
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError(f"Cannot find optimizer {name}")

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined. Note that set_learning_rate can mutate "
                              "the value of the learning rate of the optimizer "
                              "only when the LRScheduler of the optimizer is "
                              "undefined.")
        self.lr = lr

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        weight_master_copy = None
        if self.multi_precision and weight.dtype == _np.float16:
            weight_master_copy = weight.astype(_np.float32)
            return (weight_master_copy, self.create_state(index,
                                                          weight_master_copy))
        if weight.dtype == _np.float16 and not self.multi_precision:
            logging.warning("Accumulating with float16 in optimizer can lead "
                            "to poor accuracy or slow convergence. Consider "
                            "using multi_precision=True option of the "
                            "optimizer")
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and weight.dtype == _np.float16:
            weight_master_copy, original_state = state
            grad32 = grad.astype(_np.float32)
            self.update(index, weight_master_copy, grad32, original_state)
            weight._set_data(weight_master_copy._data.astype(weight.dtype))
        else:
            self.update(index, weight, grad, state)

    @staticmethod
    def _sym_mult(attrs, key):
        """Per-variable multiplier attr: the reference stores the dunder
        form (__lr_mult__); our var(lr_mult=...) stores the plain key —
        accept both."""
        if f"__{key}__" in attrs:
            return float(attrs[f"__{key}__"])
        if key in attrs:
            return float(attrs[key])
        return None

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                m = self._sym_mult(attr.get(name, {}), "lr_mult")
                if m is not None:
                    self.lr_mult[name] = m
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            is_weight = n.endswith("_weight")
            if not is_weight:
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                m = self._sym_mult(attr.get(name, {}), "wd_mult")
                if m is not None:
                    self.wd_mult[name] = m
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx],
                                  self.num_update)

    def _get_lrs(self, indices):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        lrs = [lr for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                lrs[i] *= self.param_dict[index].lr_mult
            elif index in self.lr_mult:
                lrs[i] *= self.lr_mult[index]
            elif index in self.idx2name:
                lrs[i] *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lrs

    def _get_lr(self, index):
        return self._get_lrs([index])[0]

    def _get_wds(self, indices):
        wds = [self.wd for _ in indices]
        for i, index in enumerate(indices):
            if index in self.param_dict:
                wds[i] *= self.param_dict[index].wd_mult
            elif index in self.wd_mult:
                wds[i] *= self.wd_mult[index]
            elif index in self.idx2name:
                wds[i] *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wds

    def _get_wd(self, index):
        return self._get_wds([index])[0]

    def __getstate__(self):
        ret = self.__dict__.copy()
        del ret["param_dict"]
        return ret

    def __setstate__(self, state):
        self.__dict__ = state
        self.param_dict = {}

    # common kwargs passed to every fused op
    def _common(self):
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw

    def _apply(self, pure_fn, weight, states, grad, **kwargs):
        """Run a fused pure update; swap results into weight/state handles.

        Row-sparse gradients take the reference's lazy_update path: the
        SAME fused update runs on just the touched rows (every fused
        update here is elementwise, so row decomposition is exact), then
        scatters back — O(touched rows) compute and memory, untouched
        rows (and their states) never move."""
        from ..ndarray.sparse import RowSparseNDArray

        if isinstance(grad, RowSparseNDArray) \
                and not getattr(self, "lazy_update", True):
            # explicit lazy_update=False: reference semantics are a full
            # dense update (momentum decays / wd applies on ALL rows)
            grad = grad.tostype("default")
        if isinstance(grad, RowSparseNDArray):
            import jax.numpy as jnp

            idx = grad._rs_indices
            gv = grad._rs_values
            w = _raw(weight)
            w_rows = jnp.take(w, idx, axis=0)
            s_raws = [_raw(s) for s in states]
            s_rows = [jnp.take(s, idx, axis=0) for s in s_raws]
            res = pure_fn(w_rows, gv.astype(w_rows.dtype), *s_rows,
                          **kwargs)
            weight._set_data(w.at[idx].set(res[0]))
            for s, s_raw, new in zip(states, s_raws, res[1:]):
                s._set_data(s_raw.at[idx].set(new))
            return
        # one cached jitted program per (kernel, static hyper-params) —
        # same trace structure as the grouped multi-tensor path, so the
        # two produce bitwise-identical weights
        res = _op.fused_dispatch(pure_fn, _raw(weight), _raw(grad),
                                 [_raw(s) for s in states], kwargs)
        weight._set_data(res[0])
        for s, new in zip(states, res[1:]):
            s._set_data(new)


register = Optimizer.register
create = Optimizer.create_optimizer


@register
class SGD(Optimizer):
    """SGD with momentum (reference: sgd_update / sgd_mom_update /
    mp_sgd_* kernels, src/operator/optimizer_op.cc)."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        import jax.numpy as jnp

        return _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = self._common()
        if state is not None:
            self._apply(_op.sgd_mom_update_pure, weight, [state], grad,
                        lr=lr, wd=wd, momentum=self.momentum, **kw)
        else:
            self._apply(_op.sgd_update_pure, weight, [], grad, lr=lr, wd=wd,
                        **kw)


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference: nag_mom_update)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        import jax.numpy as jnp

        return _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = self._common()
        if state is not None:
            self._apply(_op.nag_mom_update_pure, weight, [state], grad,
                        lr=lr, wd=wd, momentum=self.momentum, **kw)
        else:
            self._apply(_op.sgd_update_pure, weight, [], grad, lr=lr, wd=wd,
                        **kw)


@register
class Adam(Optimizer):
    """Adam (reference: adam_update kernel; bias correction folded into lr
    exactly as python/mxnet/optimizer/optimizer.py does)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        import jax.numpy as jnp

        return (_from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)),
                _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        self._apply(_op.adam_update_pure, weight, [mean, var], grad, lr=lr,
                    wd=wd, beta1=self.beta1, beta2=self.beta2,
                    epsilon=self.epsilon, **self._common())


@register
class Adamax(Optimizer):
    """AdaMax — infinity-norm Adam variant (reference: Adamax python impl)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        import jax.numpy as jnp

        return (_from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)),
                _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1.0 - self.beta1 ** t)
        m_t, u_t = state
        g = _raw(grad) * self.rescale_grad + wd * _raw(weight)
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        new_m = self.beta1 * _raw(m_t) + (1.0 - self.beta1) * g
        new_u = jnp.maximum(self.beta2 * _raw(u_t), jnp.abs(g))
        m_t._set_data(new_m)
        u_t._set_data(new_u)
        weight._set_data(_raw(weight) - lr * new_m / (new_u + 1e-8))


@register
class Nadam(Optimizer):
    """Nesterov Adam (reference: Nadam python impl)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        import jax.numpy as jnp

        return (_from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)),
                _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)))

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        g = _raw(grad) * self.rescale_grad + wd * _raw(weight)
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t *
                                                        self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 **
                                     ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        g_prime = g / (1.0 - self.m_schedule)
        new_m = self.beta1 * _raw(m_t) + (1.0 - self.beta1) * g
        new_v = self.beta2 * _raw(v_t) + (1.0 - self.beta2) * g * g
        m_t_prime = new_m / (1.0 - m_schedule_next)
        v_t_prime = new_v / (1.0 - self.beta2 ** t)
        m_t_bar = ((1.0 - momentum_t) * g_prime
                   + momentum_t_1 * m_t_prime)
        m_t._set_data(new_m)
        v_t._set_data(new_v)
        weight._set_data(_raw(weight) - lr * m_t_bar
                         / (jnp.sqrt(v_t_prime) + self.epsilon))


@register
class RMSProp(Optimizer):
    """RMSProp, centered or not (reference: rmsprop_update /
    rmspropalex_update kernels)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        import jax.numpy as jnp

        z = lambda: _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype))
        if self.centered:
            return (z(), z(), z())  # n, g, delta
        return (z(),)  # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = self._common()
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g, delta = state
            self._apply(_op.rmspropalex_update_pure, weight, [n, g, delta],
                        grad, lr=lr, wd=wd, gamma1=self.gamma1,
                        gamma2=self.gamma2, epsilon=self.epsilon, **kw)
        else:
            (n,) = state
            self._apply(_op.rmsprop_update_pure, weight, [n], grad, lr=lr,
                        wd=wd, gamma1=self.gamma1, epsilon=self.epsilon,
                        **kw)


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference: AdaGrad python impl over _internal ops)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        import jax.numpy as jnp

        return _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._apply(_op.adagrad_update_pure, weight, [state], grad, lr=lr,
                    wd=wd, epsilon=self.float_stable_eps, **self._common())


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference: AdaDelta python impl)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        import jax.numpy as jnp

        return (_from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)),
                _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        acc_g, acc_delta = state
        self._apply(_op.adadelta_update_pure, weight, [acc_g, acc_delta],
                    grad, rho=self.rho, epsilon=self.epsilon, wd=wd,
                    **self._common())


@register
class Ftrl(Optimizer):
    """FTRL-proximal (reference: ftrl_update kernel)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        import jax.numpy as jnp

        return (_from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)),
                _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        z, n = state
        self._apply(_op.ftrl_update_pure, weight, [z, n], grad, lr=lr,
                    wd=wd, lamda1=self.lamda1, beta=self.beta,
                    **self._common())


@register
class Signum(Optimizer):
    """Signum / SignSGD (reference: signum_update / signsgd_update)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        import jax.numpy as jnp

        return _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = self._common()
        if state is not None:
            self._apply(_op.signum_update_pure, weight, [state], grad,
                        lr=lr, wd=wd, momentum=self.momentum,
                        wd_lh=self.wd_lh, **kw)
        else:
            self._apply(_op.signsgd_update_pure, weight, [], grad, lr=lr,
                        wd=wd, **kw)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference: SGLD python impl)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        import jax
        import jax.numpy as jnp

        from ..random import next_key

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = _raw(grad) * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        noise = jax.random.normal(next_key(), weight.shape,
                                  dtype=_raw(weight).dtype) * math.sqrt(lr)
        weight._set_data(_raw(weight) - lr / 2 * (g + wd * _raw(weight))
                         + noise)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: DCASGD python impl)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        import jax.numpy as jnp

        if self.momentum == 0.0:
            return (None, weight.copy())
        return (_from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)),
                weight.copy())

    def update(self, index, weight, grad, state):
        import jax.numpy as jnp

        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        g = _raw(grad) * self.rescale_grad
        if self.clip_gradient is not None:
            g = jnp.clip(g, -self.clip_gradient, self.clip_gradient)
        mom, previous_weight = state
        w = _raw(weight)
        pw = _raw(previous_weight)
        comp = g + wd * w + self.lamda * g * g * (w - pw)
        if mom is not None:
            new_mom = self.momentum * _raw(mom) - lr * comp
            mom._set_data(new_mom)
            delta = new_mom
        else:
            delta = -lr * comp
        previous_weight._set_data(w)
        weight._set_data(w + delta)


@register
class LAMB(Optimizer):
    """LAMB layerwise-adaptive large-batch optimizer (reference:
    lamb_update_phase1/2 kernels, ≥1.6)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.bias_correction = bias_correction
        # trust-ratio norms need the whole tensor: row-sparse grads must
        # densify rather than take the lazy row-slice path
        self.lazy_update = False

    def create_state(self, index, weight):
        import jax.numpy as jnp

        return (_from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)),
                _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        mean, var = state
        # phase1 + trust-ratio norms + phase2 as ONE fused dispatch; the
        # bias-correction denominators fold on the host (x/1.0 is an
        # IEEE identity when correction is off)
        if self.bias_correction:
            denom1 = 1.0 - self.beta1 ** t
            denom2 = 1.0 - self.beta2 ** t
        else:
            denom1 = 1.0
            denom2 = 1.0
        kw = {}
        if self.lower_bound is not None:
            kw["lower_bound"] = self.lower_bound
        if self.upper_bound is not None:
            kw["upper_bound"] = self.upper_bound
        self._apply(_op.lamb_fused_update_pure, weight, [mean, var], grad,
                    lr=lr, wd=wd, denom1=denom1, denom2=denom2,
                    beta1=self.beta1, beta2=self.beta2,
                    epsilon=self.epsilon, **kw, **self._common())


@register
class LARS(Optimizer):
    """LARS layer-wise adaptive SGD for large-batch training
    (reference: LBSGD optimizer + lars_update kernels ≥1.6).

    1-D parameters (biases, BN gamma/beta) take the plain SGD-momentum
    step — the reference's skip list — since norm-ratio adaptation on
    them destabilizes training."""

    def __init__(self, learning_rate=0.1, momentum=0.9, eta=0.001,
                 epsilon=1e-9, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.eta = eta
        self.epsilon = epsilon

    def create_state(self, index, weight):
        import jax.numpy as jnp

        return _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        kw = self._common()
        if len(weight.shape) <= 1:
            self._apply(_op.sgd_mom_update_pure, weight, [state], grad,
                        lr=lr, wd=wd, momentum=self.momentum, **kw)
        else:
            self._apply(_op.lars_update_pure, weight, [state], grad,
                        lr=lr, wd=wd, momentum=self.momentum,
                        eta=self.eta, epsilon=self.epsilon, **kw)


@register
class FTML(Optimizer):
    """Follow The Moving Leader (reference: FTML optimizer + ftml_update
    kernel ≥1.2; Zheng & Kwok, ICML 2017)."""

    def __init__(self, learning_rate=0.0025, beta1=0.6, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        import jax.numpy as jnp

        # d, v, z
        return tuple(
            _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype))
            for _ in range(3))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        lr = self._get_lr(index)
        kw = self._common()
        # reference quirk: ftml_update takes clip_grad, not clip_gradient
        kw["clip_grad"] = kw.pop("clip_gradient", -1.0)
        # the step-count coefficients fold on the host exactly as
        # ftml_update_pure applied them, so lr/t never shape the trace
        self._apply(_op.ftml_fused_update_pure, weight, list(state), grad,
                    c_over_lr=(1.0 - self.beta1 ** t) / lr,
                    coef2=1.0 - self.beta2 ** t,
                    wd=self._get_wd(index), beta1=self.beta1,
                    beta2=self.beta2, epsilon=self.epsilon, **kw)


@register
class LBSGD(LARS):
    """Large-Batch SGD (reference: LBSGD optimizer ≥1.2): LARS layer-wise
    adaptive rates plus an lr warmup schedule for the batch-scaled lr.

    The reference's accounting knobs (``batch_scale``,
    ``updates_per_epoch``, ``begin_epoch``/``num_epochs``) translate to:
    effective lr ramps from ``learning_rate`` to ``learning_rate *
    batch_scale`` over ``warmup_epochs * updates_per_epoch`` updates,
    by the chosen ``warmup_strategy`` ('linear'|'power2'|'sqrt';
    anything else disables warmup).  ``begin_epoch``/``num_epochs`` are
    accepted for reference signature compatibility only — they fed the
    reference's internal epoch bookkeeping, which ``updates_per_epoch``
    already determines here."""

    def __init__(self, momentum=0.0, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32,
                 begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(momentum=momentum, **kwargs)
        # 'lars' (a reference-valid strategy whose ramp follows the lars
        # coefficients) is approximated by the linear ramp; unknown
        # strategies must not silently jump to the full scaled lr
        if warmup_strategy == "lars":
            warmup_strategy = "linear"
        elif warmup_strategy not in ("linear", "power2", "sqrt", None):
            raise MXNetError(
                f"LBSGD: unknown warmup_strategy {warmup_strategy!r} "
                f"(expected linear|power2|sqrt|lars|None)")
        self.warmup_strategy = warmup_strategy
        self.batch_scale = float(batch_scale)
        self.warmup_updates = max(1, int(warmup_epochs)
                                  * max(1, int(updates_per_epoch)))

    def _get_lr(self, index):
        lr = super()._get_lr(index)
        t = max(self._index_update_count.get(index, 1), 1)
        frac = min(t / self.warmup_updates, 1.0)
        if self.warmup_strategy == "linear":
            pass
        elif self.warmup_strategy == "power2":
            frac = frac * frac
        elif self.warmup_strategy == "sqrt":
            frac = frac ** 0.5
        else:
            return lr * self.batch_scale
        return lr * (1.0 + frac * (self.batch_scale - 1.0))


@register
class AdamW(Optimizer):
    """Adam with decoupled weight decay (reference: contrib.AdamW)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        import jax.numpy as jnp

        return (_from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)),
                _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype)))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr *= math.sqrt(coef2) / coef1
        mean, var = state
        self._apply(_op.adamw_update_pure, weight, [mean, var], grad,
                    lr=lr, wd=wd, beta1=self.beta1, beta2=self.beta2,
                    epsilon=self.epsilon, **self._common())


@register
class Test(Optimizer):
    """Test optimizer (reference: mx.optimizer.Test) — w -= lr*grad only."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)

    def create_state(self, index, weight):
        import jax.numpy as jnp

        return _from_jax(jnp.zeros(weight.shape, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        weight._set_data(_raw(weight)
                         - self.lr * self.rescale_grad * _raw(grad))


class Updater:
    """Applies an Optimizer to (index, grad, weight) triples, owning states
    (reference: mx.optimizer.Updater — the local/server-side update path)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = False

    def __call__(self, index, grad, weight):
        if not isinstance(index, (list, tuple)):
            indices = [index]
            grads = [grad]
            weights = [weight]
        else:
            indices, grads, weights = index, grad, weight
        for i, g, w in zip(indices, grads, weights):
            if i not in self.states:
                self.states[i] = \
                    self.optimizer.create_state_multi_precision(i, w)
                self.states_synced[i] = True
            self.optimizer.update_multi_precision(i, w, g, self.states[i])

    def sync_state_context(self, state, context):
        return state

    def set_states(self, states):
        states = pickle.loads(states)
        if isinstance(states, tuple) and len(states) == 2:
            self.states, self.optimizer = states
        else:
            self.states = states
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        return pickle.dumps((self.states, self.optimizer)
                            if dump_optimizer else self.states)


def get_updater(optimizer):
    return Updater(optimizer)
