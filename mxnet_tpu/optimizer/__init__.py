"""Optimizer API (reference: python/mxnet/optimizer/)."""

from . import optimizer
from . import lr_scheduler
from .optimizer import (Optimizer, SGD, NAG, Adam, Adamax, Nadam, RMSProp,
                        AdaGrad, AdaDelta, Ftrl, Signum, SGLD, DCASGD, LAMB,
                        LARS, LBSGD, FTML, AdamW, Test, Updater, get_updater,
                        register, create)
from .lr_scheduler import (LRScheduler, FactorScheduler, MultiFactorScheduler,
                           PolyScheduler, CosineScheduler)
from . import grouped
from .grouped import GroupedUpdater

# reference alias: mx.optimizer.ccSGD etc. are deprecated; keep `create`
# as the canonical factory (mx.optimizer.create / Optimizer.create_optimizer)
