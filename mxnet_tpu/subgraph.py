"""Subgraph partitioning API.

Reference parity: src/operator/subgraph/ (SubgraphProperty,
MXNET_REGISTER_SUBGRAPH_BACKEND/PROPERTY) + Symbol.optimize_for — the
mechanism MKLDNN fusion and TensorRT offload plug into: select
supported nodes, group maximal acyclic regions, hand each region to a
backend executor.

TPU-first redesign: the flagship backend is "XLA" — a partitioned
region becomes ONE ``_subgraph_exec`` node whose evaluation
jit-compiles the whole region (cached on the node), so the legacy
Symbol/Module path gets whole-region XLA fusion exactly the way
hybridize() does for gluon.  Custom properties subclass
SubgraphProperty and register with ``register_subgraph_property``
(op_filter is the reference's SupportedOps contract).
"""

from __future__ import annotations

import itertools

from .base import MXNetError
from .ops import registry as _registry

_BACKENDS = {}


class SubgraphProperty:
    """Node-selection contract (reference: SubgraphProperty)."""

    #: regions smaller than this stay unpartitioned
    min_size = 1

    def op_filter(self, op_name, attrs):
        """True if the op may live inside a partitioned region."""
        raise NotImplementedError

    def name(self):
        return self.__class__.__name__


class XLASubgraphProperty(SubgraphProperty):
    """Everything the registry can trace is XLA-compilable; only opaque
    host-level ops (mutating optimizer wrappers, IO) stay outside."""

    min_size = 2  # a single op gains nothing from its own jit region

    def op_filter(self, op_name, attrs):
        try:
            opdef = _registry.get(op_name)
        except Exception:
            return False
        return not getattr(opdef, "opaque", False)


def register_subgraph_property(backend, prop):
    """Reference: MXNET_REGISTER_SUBGRAPH_PROPERTY."""
    if not isinstance(prop, SubgraphProperty):
        raise MXNetError("prop must be a SubgraphProperty instance")
    _BACKENDS[backend] = prop
    return prop


def list_backends():
    return sorted(_BACKENDS)


register_subgraph_property("XLA", XLASubgraphProperty())

_SUBGRAPH_COUNTER = itertools.count()


def partition(sym, backend="XLA"):
    """Group maximal supported regions into ``_subgraph_exec`` nodes
    (reference: the BuildSubgraph pass behind Symbol.optimize_for).

    Symbol identity is by NAME (out_index views share their node), so
    the whole pass is name-keyed.  A node joins a producer's group only
    when that cannot create a cycle through out-of-group nodes
    (tracked via transitive group-dependency sets).  Regions expose as
    many outputs as the outside graph consumes (multi-output node).
    """
    from . import symbol as _sym_mod

    prop = _BACKENDS.get(backend)
    if prop is None:
        raise MXNetError(
            f"unknown subgraph backend '{backend}' "
            f"(registered: {list_backends()})")

    # _topo dedups by object id; out_index VIEWS of one node appear as
    # extra entries sharing the name — the pass is name-keyed, so keep
    # only the first entry per name
    topo, _seen_names = [], set()
    for n in sym._topo():
        if n.name not in _seen_names:
            _seen_names.add(n.name)
            topo.append(n)
    by_name = {n.name: n for n in topo}
    supported = {n.name: (n.op is not None
                          and prop.op_filter(n.op, n.attrs))
                 for n in topo}

    group_of = {}            # node name -> gid
    members = {}             # gid -> [node names in topo order]
    depends_on = {}          # node name -> set of gids upstream of it
    group_deps = {}          # gid -> set of gids it depends on (direct)
    gid_counter = itertools.count()

    def _gclosure(gids, acc=None):
        """Transitive closure over group_deps."""
        acc = set() if acc is None else acc
        for g in gids:
            if g not in acc:
                acc.add(g)
                _gclosure(group_deps.get(g, ()), acc)
        return acc

    def _input_dep_groups(i):
        """Group-closed set of gids that input entry `i` depends on
        (including its own group)."""
        base = set(depends_on.get(i.name, ()))
        g = group_of.get(i.name)
        if g is not None:
            base.add(g)
        return _gclosure(base)

    for n in topo:
        node_deps = set()
        for i in n.inputs:
            node_deps |= depends_on.get(i.name, set())
            g = group_of.get(i.name)
            if g is not None:
                node_deps.add(g)
        if not supported[n.name]:
            depends_on[n.name] = node_deps
            continue
        cand = sorted({group_of[i.name] for i in n.inputs
                       if i.name in group_of})
        gid = None
        for g in cand:
            # joining g is safe iff no input path OUTSIDE g transitively
            # depends on g (group-closed): such a path would route g's
            # output around the region and back in — a cycle once each
            # group becomes one node
            if all(group_of.get(i.name) == g
                   or g not in _input_dep_groups(i)
                   for i in n.inputs):
                gid = g
                break
        if gid is None:
            gid = next(gid_counter)
            members[gid] = []
            group_deps[gid] = set()
        # the group inherits every dependency the member brings
        for i in n.inputs:
            if group_of.get(i.name) != gid:
                group_deps[gid] |= _input_dep_groups(i)
        group_deps[gid].discard(gid)
        group_of[n.name] = gid
        members[gid].append(n.name)
        depends_on[n.name] = node_deps - {gid}

    # demote undersized groups
    for gid, mem in list(members.items()):
        if len(mem) < prop.min_size:
            for nm in mem:
                del group_of[nm]
            del members[gid]

    # which member outputs (name, out_index) are visible outside?
    consumers_outside = {gid: [] for gid in members}
    head_name = topo[-1].name
    for n in topo:
        for i in n.inputs:
            g = group_of.get(i.name)
            if g is not None and group_of.get(n.name) != g:
                key = (i.name, i.out_index)
                if key not in consumers_outside[g]:
                    consumers_outside[g].append(key)
    hg = group_of.get(head_name)
    if hg is not None:
        key = (head_name, sym.out_index)
        if key not in consumers_outside[hg]:
            consumers_outside[hg].append(key)

    # rebuild graph.  rebuilt[name] is either a node-level Symbol or,
    # for region members, a {out_index: Symbol} map onto the merged
    # node's outputs.
    rebuilt = {}

    def lookup(entry):
        r = rebuilt[entry.name]
        if isinstance(r, dict):
            return r[entry.out_index]
        if entry.out_index:
            return r[entry.out_index]
        return r

    last_member = {gid: mem[-1] for gid, mem in members.items()}
    for n in topo:
        if n.op is None:
            v = _sym_mod.var(n.name)
            v.attrs.update(n.attrs)
            v._attr_dict.update(n._attr_dict)
            rebuilt[n.name] = v
            continue
        gid = group_of.get(n.name)
        if gid is None:
            ins = [lookup(i) for i in n.inputs]
            rebuilt[n.name] = _sym_mod.apply_op(n.op, *ins,
                                                name=n.name, **n.attrs)
            continue
        if n.name != last_member[gid]:
            continue  # emitted at the region's last node
        mem = members[gid]
        mem_set = set(mem)
        ext, seen = [], set()
        for nm in mem:
            for i in by_name[nm].inputs:
                key = (i.name, i.out_index)
                if i.name not in mem_set and key not in seen:
                    seen.add(key)
                    ext.append(i)
        visible = consumers_outside[gid] or [(mem[-1], 0)]
        node = _sym_mod.Symbol(
            "_subgraph_exec",
            f"xla_subgraph{next(_SUBGRAPH_COUNTER)}",
            [lookup(i) for i in ext],
            {"__backend__": backend},
            n_outputs=len(visible))
        node._attr_dict["__members__"] = [by_name[nm] for nm in mem]
        node._attr_dict["__ext__"] = [(i.name, i.out_index) for i in ext]
        node._attr_dict["__visible__"] = list(visible)
        node._attr_dict["__jit_cache__"] = {}
        for k, (nm, oi) in enumerate(visible):
            slot = rebuilt.setdefault(nm, {})
            if not isinstance(slot, dict):  # shouldn't happen
                slot = rebuilt[nm] = {}
            slot[oi] = node[k] if len(visible) > 1 else node
    head = lookup(_Entry(head_name, sym.out_index))
    return head


class _Entry:
    __slots__ = ("name", "out_index")

    def __init__(self, name, out_index):
        self.name = name
        self.out_index = out_index


def subgraph_exec(node, ext_vals):
    """Evaluate one partitioned region as a single jitted program
    (called from Symbol._eval_node).

    Execution-scope injection matches _eval_node's contract: random
    members receive fresh PRNG keys (passed as jit arguments, one per
    random op per call), and mode-dependent members get _is_training
    from the autograd scope (one compiled program per mode).
    """
    import jax

    from . import autograd as _ag
    from .random import next_key

    members = node._attr_dict["__members__"]
    ext = node._attr_dict["__ext__"]
    visible = node._attr_dict["__visible__"]
    cache = node._attr_dict["__jit_cache__"]

    random_members = [m.name for m in members
                      if _registry.get(m.op).random
                      and m.attrs.get("_key") is None]
    training = bool(_ag.is_training())

    fn = cache.get(training)
    if fn is None:
        def run(vals, keys):
            env = {}
            for (nm, oi), v in zip(ext, vals):
                env.setdefault(nm, {})[oi] = v
            for m in members:
                ins = []
                for i in m.inputs:
                    slot = env[i.name]
                    if isinstance(slot, dict):
                        v = slot.get(i.out_index)
                    else:
                        v = slot[i.out_index] \
                            if isinstance(slot, (tuple, list)) else slot
                    ins.append(v)
                opdef = _registry.get(m.op)
                from .symbol.symbol import _split_kw_inputs

                ins, kw_bound, attrs_nk = _split_kw_inputs(ins, m.attrs)
                kwargs = {k: v for k, v in attrs_nk.items()
                          if not k.startswith("__")}
                kwargs.update(kw_bound)
                if opdef.mode_dependent \
                        and kwargs.get("_is_training") is None:
                    kwargs["_is_training"] = training
                if opdef.random and kwargs.get("_key") is None:
                    kwargs["_key"] = keys[m.name]
                out = opdef.fn(*ins, **kwargs)
                env[m.name] = out if isinstance(out, (tuple, list)) \
                    else (out,)
            outs = []
            for nm, oi in visible:
                slot = env[nm]
                outs.append(slot[oi] if isinstance(slot, (tuple, dict))
                            else slot)
            return tuple(outs)

        fn = jax.jit(run)
        cache[training] = fn
    keys = {nm: next_key() for nm in random_members}
    out = fn(list(ext_vals), keys)
    return out if len(visible) > 1 else out[0]
