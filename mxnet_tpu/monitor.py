"""Monitor — per-layer output statistics during training (reference:
python/mxnet/monitor.py; installed via ``Module.fit(monitor=...)``).

Reference mechanism: a callback hooked into every op execution
(MXExecutorSetMonitorCallback) collects outputs between ``tic()`` and
``toc()``.  Under XLA the whole graph is ONE compiled program with no
per-op callbacks, so the TPU-native Monitor evaluates the matching
interior nodes eagerly from the executor's current arguments at
``toc()`` time — same mode, same arguments, debugging-priced (extra
eager evaluation; install only while diagnosing, exactly like the
reference's advice).  One caveat vs the reference's passive callback:
stochastic ops (Dropout) re-sample under a monitor-local PRNG key, so
their statistics are representative, not the exact masks of the
monitored forward — the global key stream is left untouched (the
observer never changes the experiment).
"""

from __future__ import annotations

import re

from .ndarray.ndarray import NDArray, _from_jax


def _default_stat(x):
    import jax.numpy as jnp

    return jnp.abs(x).mean()


def nonfinite_fraction(x):
    """Stat function for NaN-hunting: the fraction of non-finite values
    in a node's output.  ``Monitor(1, stat_func=monitor.nonfinite_fraction,
    pattern='.*')`` localizes WHICH layer first produces NaN/Inf when the
    numerics guard (docs/resilience.md "Numerical resilience") reports
    skipped steps."""
    import jax.numpy as jnp

    return 1.0 - jnp.mean(jnp.isfinite(x.astype(jnp.float32))
                          .astype(jnp.float32))


class Monitor:
    """Collect per-node output statistics every ``interval`` batches.

    Parameters mirror the reference: ``interval`` (batches between
    collections), ``stat_func`` (raw-array → scalar, default mean |x|),
    ``pattern`` (regex on node names), ``sort`` (sort results by name).
    """

    def __init__(self, interval, stat_func=None, pattern=".*",
                 sort=False):
        self.interval = int(interval)
        self.stat_func = stat_func or _default_stat
        self.re_pattern = re.compile(pattern)
        self.sort = sort
        self.queue = []
        self.step = 0
        self.activated = False
        self._exes = []

    def install(self, exe):
        """Register an executor to monitor (reference: install on every
        executor in the group).  A new executor for the SAME symbol
        (rebind) evicts the stale one — toc() must not keep reporting
        from dead pre-rebind arg arrays."""
        self._exes = [e for e in self._exes
                      if e is not exe and e._symbol is not exe._symbol]
        self._exes.append(exe)

    def tic(self):
        """Start collecting for this batch if the interval hits."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def _interior_nodes(self, exe):
        sym = exe._symbol
        return [n for n in sym._topo()
                if n.op is not None and self.re_pattern.match(n.name)]

    def toc(self):
        """Collect stats from all installed executors; returns a list of
        (step, node_name, stat) with stat an NDArray scalar."""
        if not self.activated:
            return []
        import jax

        from . import autograd as _ag
        from . import random as _random

        res = []
        for exe in self._exes:
            env = {name: arr._data
                   for name, arr in exe.arg_dict.items()}
            env.update({name: arr._data
                        for name, arr in exe.aux_dict.items()})
            # re-evaluate in the SAME mode the monitored forward ran in
            # (dropout/BN stats must match the training step), and under
            # a LOCAL key scope so the eval does not advance the global
            # PRNG stream — the observer must not change the experiment
            mode = _ag.train_mode \
                if getattr(exe, "_last_is_train", False) \
                else _ag.predict_mode
            # one shared memo per executor: each node eval reuses every
            # ancestor already computed (one forward-equivalent pass,
            # not O(nodes^2))
            cache = {}
            for node in self._interior_nodes(exe):
                try:
                    with mode(), _random.key_scope(
                            jax.random.PRNGKey(self.step)):
                        out = node._eval_node(node, env, cache)
                except Exception:
                    continue  # heads needing absent inputs (labels etc.)
                outs = list(out) if isinstance(out, tuple) else [out]
                for i, o in enumerate(outs):
                    name = node.name + (f"_output{i}" if len(outs) > 1
                                        else "_output")
                    res.append((self.step, name,
                                _from_jax(self.stat_func(o))))
        self.activated = False
        if self.sort:
            res.sort(key=lambda t: t[1])
        self.queue = res
        return res

    def toc_print(self):
        """Collect and log (reference: toc_print)."""
        import logging

        for step, name, stat in self.toc():
            val = stat.asnumpy() if isinstance(stat, NDArray) else stat
            logging.info("Batch: %7d %30s %s", step, name, str(val))
