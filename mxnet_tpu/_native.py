"""ctypes loader for the native C++ library.

Reference parity: python/mxnet/base.py's ``_LIB`` dll loading — the FFI
boundary of the rebuild (SURVEY.md L5).  The library is optional: every
consumer has a pure-python fallback, so an unbuilt tree still works
(``make -C src`` builds it).
"""

from __future__ import annotations

import ctypes
import os

_LIB = None
_TRIED = False


def lib():
    """Return the loaded native library or None."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    candidates = [
        os.path.join(here, "src", "libmxtpu_io.so"),
        os.path.join(here, "libmxtpu_io.so"),
    ]
    for path in candidates:
        if os.path.exists(path):
            try:
                _LIB = ctypes.CDLL(path)
                _declare(_LIB)
                break
            except OSError:
                _LIB = None
    return _LIB


def _declare(L):
    c = ctypes
    L.mxtpu_recio_open_read.restype = c.c_void_p
    L.mxtpu_recio_open_read.argtypes = [c.c_char_p]
    L.mxtpu_recio_close_read.argtypes = [c.c_void_p]
    L.mxtpu_recio_scan.restype = c.c_int64
    L.mxtpu_recio_scan.argtypes = [c.c_void_p,
                                   c.POINTER(c.POINTER(c.c_int64))]
    L.mxtpu_recio_read_at.restype = c.c_int64
    L.mxtpu_recio_read_at.argtypes = [c.c_void_p, c.c_int64,
                                      c.POINTER(c.POINTER(c.c_char))]
    L.mxtpu_free.argtypes = [c.POINTER(c.c_char)]
    L.mxtpu_free_i64.argtypes = [c.POINTER(c.c_int64)]
    L.mxtpu_recio_open_write.restype = c.c_void_p
    L.mxtpu_recio_open_write.argtypes = [c.c_char_p, c.c_int]
    L.mxtpu_recio_write.restype = c.c_int64
    L.mxtpu_recio_write.argtypes = [c.c_void_p, c.c_char_p, c.c_int64]
    L.mxtpu_recio_close_write.argtypes = [c.c_void_p]
    L.mxtpu_prefetcher_create.restype = c.c_void_p
    L.mxtpu_prefetcher_create.argtypes = [c.c_char_p, c.c_int, c.c_int,
                                          c.c_uint64]
    L.mxtpu_prefetcher_size.restype = c.c_int64
    L.mxtpu_prefetcher_size.argtypes = [c.c_void_p]
    L.mxtpu_prefetcher_next.restype = c.c_int64
    L.mxtpu_prefetcher_next.argtypes = [c.c_void_p,
                                        c.POINTER(c.POINTER(c.c_char))]
    L.mxtpu_prefetcher_reset.argtypes = [c.c_void_p, c.c_uint64]
    L.mxtpu_prefetcher_destroy.argtypes = [c.c_void_p]


class NativeRecordReader:
    """Random-access reader over the native codec."""

    def __init__(self, path):
        L = lib()
        if L is None:
            raise OSError("native library not built (make -C src)")
        self._L = L
        self._h = L.mxtpu_recio_open_read(path.encode())
        if not self._h:
            raise OSError(f"cannot open {path}")

    def scan(self):
        ptr = ctypes.POINTER(ctypes.c_int64)()
        n = self._L.mxtpu_recio_scan(self._h, ctypes.byref(ptr))
        if n < 0:
            raise OSError("corrupt record file")
        out = [ptr[i] for i in range(n)]
        self._L.mxtpu_free_i64(ptr)
        return out

    def read_at(self, offset):
        ptr = ctypes.POINTER(ctypes.c_char)()
        n = self._L.mxtpu_recio_read_at(self._h, offset,
                                        ctypes.byref(ptr))
        if n < 0:
            raise OSError("read failed")
        data = ctypes.string_at(ptr, n)
        self._L.mxtpu_free(ptr)
        return data

    def close(self):
        if self._h:
            self._L.mxtpu_recio_close_read(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativeRecordWriter:
    def __init__(self, path, append=False):
        L = lib()
        if L is None:
            raise OSError("native library not built (make -C src)")
        self._L = L
        self._h = L.mxtpu_recio_open_write(path.encode(),
                                           1 if append else 0)
        if not self._h:
            raise OSError(f"cannot open {path}")

    def write(self, data):
        return self._L.mxtpu_recio_write(self._h, data, len(data))

    def close(self):
        if self._h:
            self._L.mxtpu_recio_close_write(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class NativePrefetcher:
    """Threaded record prefetcher (dmlc::ThreadedIter analog)."""

    def __init__(self, path, n_threads=4, shuffle=False, seed=0):
        L = lib()
        if L is None:
            raise OSError("native library not built (make -C src)")
        self._L = L
        self._h = L.mxtpu_prefetcher_create(path.encode(), n_threads,
                                            1 if shuffle else 0, seed)
        if not self._h:
            raise OSError(f"cannot open {path}")

    def __len__(self):
        return self._L.mxtpu_prefetcher_size(self._h)

    def next(self):
        ptr = ctypes.POINTER(ctypes.c_char)()
        n = self._L.mxtpu_prefetcher_next(self._h, ctypes.byref(ptr))
        if n < 0:
            return None
        data = ctypes.string_at(ptr, n)
        self._L.mxtpu_free(ptr)
        return data

    def reset(self, seed=0):
        self._L.mxtpu_prefetcher_reset(self._h, seed)

    def close(self):
        if self._h:
            self._L.mxtpu_prefetcher_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def available():
    return lib() is not None


# libmxtpu_img.so loads independently: a host without libjpeg keeps the
# recordio/prefetch fast path
_IMG_LIB = None
_IMG_TRIED = False


def img_lib():
    global _IMG_LIB, _IMG_TRIED
    if _IMG_TRIED:
        return _IMG_LIB
    _IMG_TRIED = True
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for path in (os.path.join(here, "src", "libmxtpu_img.so"),
                 os.path.join(here, "libmxtpu_img.so")):
        if os.path.exists(path):
            try:
                L = ctypes.CDLL(path)
                c = ctypes
                L.MXTPUHasJpeg.restype = c.c_int
                L.MXTPUImageDecodeAugment.restype = c.c_int
                L.MXTPUImageDecodeAugment.argtypes = [
                    c.POINTER(c.c_char_p), c.POINTER(c.c_size_t),
                    c.c_int, c.c_int, c.c_int, c.c_int,
                    c.POINTER(c.c_int32), c.POINTER(c.c_uint64),
                    c.POINTER(c.c_uint8), c.c_float,
                    c.POINTER(c.c_float), c.POINTER(c.c_float), c.c_int,
                    c.POINTER(c.c_float), c.POINTER(c.c_int32)]
                _IMG_LIB = L
                break
            except OSError:
                _IMG_LIB = None
    return _IMG_LIB


def has_jpeg():
    L = img_lib()
    return bool(L is not None and L.MXTPUHasJpeg())


def decode_augment_batch(payloads, out, resize_short=-1, crop_modes=None,
                         seeds=None, mirror=None, scale=1.0, mean=None,
                         std=None, n_threads=4):
    """Batch JPEG decode + augment into ``out`` (N, 3, H, W) float32.

    Reference: iter_image_recordio_2.cc's threaded decode+augment loop.
    crop_modes per image: -1 center, -2 random (seeded by seeds).
    Returns a numpy int32 status array (1 decoded, 0 = caller must fall
    back, e.g. PNG payloads).
    """
    import numpy as np

    L = img_lib()
    if L is None:
        raise OSError("native jpeg path not built (make -C src)")
    n = len(payloads)
    # hard checks, not asserts: a shape mismatch here is an
    # out-of-bounds C write, and python -O strips asserts
    if not (out.ndim == 4 and out.shape[0] == n and out.shape[1] == 3
            and out.dtype == np.float32 and out.flags.c_contiguous):
        raise ValueError(
            f"out must be C-contiguous float32 (n={n}, 3, H, W); got "
            f"{out.dtype} {out.shape}")
    c = ctypes
    # bytes are immutable: pass their buffers by pointer, no copy (the
    # payloads list keeps them alive for this synchronous call)
    payloads = [bytes(p) for p in payloads]
    ptrs = (c.c_char_p * n)(*payloads)
    sizes = (c.c_size_t * n)(*[len(p) for p in payloads])
    cm = np.full(n, -1, np.int32) if crop_modes is None \
        else np.asarray(crop_modes, np.int32)
    sd = np.zeros(n, np.uint64) if seeds is None \
        else np.asarray(seeds, np.uint64)
    mr = np.zeros(n, np.uint8) if mirror is None \
        else np.asarray(mirror, np.uint8)
    mean = np.asarray(mean if mean is not None else [0, 0, 0],
                      np.float32)
    std = np.asarray(std if std is not None else [1, 1, 1], np.float32)
    status = np.zeros(n, np.int32)
    L.MXTPUImageDecodeAugment(
        ptrs, sizes, n, int(resize_short), int(out.shape[2]),
        int(out.shape[3]),
        cm.ctypes.data_as(c.POINTER(c.c_int32)),
        sd.ctypes.data_as(c.POINTER(c.c_uint64)),
        mr.ctypes.data_as(c.POINTER(c.c_uint8)),
        float(scale), mean.ctypes.data_as(c.POINTER(c.c_float)),
        std.ctypes.data_as(c.POINTER(c.c_float)), int(n_threads),
        out.ctypes.data_as(c.POINTER(c.c_float)),
        status.ctypes.data_as(c.POINTER(c.c_int32)))
    return status
