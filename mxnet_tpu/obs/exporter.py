"""Live metrics export: a stdlib-only Prometheus text endpoint.

NEW, fleet-observability plane (ISSUE 14).  `telemetry.REGISTRY`
already holds every counter/gauge/histogram the subsystems maintain;
this module puts an HTTP face on `MetricsRegistry.snapshot()` (plus
the fleet rollup, when a collector/FleetView is attached) so the
standard scrape stack works against a training or serving host with
ZERO new dependencies: ``http.server`` + text/plain.

- ``GET /metrics`` → Prometheus text format (version 0.0.4): counters
  as ``counter``, gauges as ``gauge``, histograms flattened to
  ``_count`` / ``_sum`` / ``_min`` / ``_max`` series (the registry
  keeps aggregate shape, not buckets — see telemetry.Histogram), and
  fleet per-rank series labelled ``{rank="N"}``.
- Metric names sanitize ``.`` / ``-`` to ``_`` under an ``mxtpu_``
  prefix: ``collective.bytes`` → ``mxtpu_collective_bytes``.
- The server is a daemon `ThreadingHTTPServer` on ``MXTPU_METRICS_PORT``
  (0 = ephemeral, the test path); scraping never touches the train
  thread — snapshot() is a dict copy under the registry's own lock
  discipline.

`ensure_from_env()` is the one-per-process bootstrap the Trainer calls
(alongside `ensure_compile_cache`): exporter + collector start when
``MXTPU_METRICS_PORT`` is set, and stay off otherwise.
"""

from __future__ import annotations

import os
import re
import threading

from .. import telemetry

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str) -> str:
    clean = _NAME_RE.sub("_", str(name))
    if not clean.startswith("mxtpu_"):
        clean = "mxtpu_" + clean
    return clean


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return format(float(v), ".10g")


def render_prometheus(snapshot, fleet_summary=None, registry=None) -> str:
    """Render a `MetricsRegistry.snapshot()` dict (+ optional
    `FleetView.summary()`) as Prometheus text exposition format."""
    lines = []
    reg = registry._metrics if registry is not None else {}

    for name in sorted(snapshot):
        val = snapshot[name]
        mname = _metric_name(name)
        if isinstance(val, dict):           # histogram summary
            lines.append(f"# TYPE {mname}_count counter")
            lines.append(f"{mname}_count {_fmt(val.get('count', 0))}")
            lines.append(f"# TYPE {mname}_sum counter")
            lines.append(f"{mname}_sum {_fmt(val.get('total', 0.0))}")
            for k in ("min", "max"):
                if isinstance(val.get(k), (int, float)):
                    lines.append(f"# TYPE {mname}_{k} gauge")
                    lines.append(f"{mname}_{k} {_fmt(val[k])}")
            continue
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            continue
        kind = "counter" if isinstance(reg.get(name),
                                       telemetry.Counter) else "gauge"
        lines.append(f"# TYPE {mname} {kind}")
        lines.append(f"{mname} {_fmt(val)}")

    if fleet_summary:
        fs = fleet_summary
        if fs.get("fleet_mfu") is not None:
            lines.append("# HELP mxtpu_fleet_mfu "
                         "step-weighted fleet MFU across ranks")
            lines.append("# TYPE mxtpu_fleet_mfu gauge")
            lines.append(f"mxtpu_fleet_mfu {_fmt(fs['fleet_mfu'])}")
        lines.append("# TYPE mxtpu_fleet_steps_total counter")
        lines.append(f"mxtpu_fleet_steps_total "
                     f"{_fmt(fs.get('steps_total', 0))}")
        lines.append("# TYPE mxtpu_fleet_ranks gauge")
        lines.append(f"mxtpu_fleet_ranks {_fmt(len(fs.get('ranks', [])))}")
        if fs.get("interval_skew") is not None:
            lines.append("# TYPE mxtpu_fleet_interval_skew gauge")
            lines.append(f"mxtpu_fleet_interval_skew "
                         f"{_fmt(fs['interval_skew'])}")
        for r, v in sorted((fs.get("interval_us") or {}).items()):
            lines.append('mxtpu_fleet_rank_interval_us'
                         f'{{rank="{r}"}} {_fmt(v)}')
    return "\n".join(lines) + "\n"


class MetricsExporter:
    """The /metrics HTTP endpoint.  ``port=0`` binds an ephemeral port
    (read it back via ``.port``); ``fleet`` is an optional FleetView
    refreshed per scrape (scrape-rate bounded, not train-loop
    bounded)."""

    def __init__(self, port=None, host="127.0.0.1", registry=None,
                 fleet=None):
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        if port is None:
            port = int(os.environ.get("MXTPU_METRICS_PORT", 0))
        self.registry = registry if registry is not None \
            else telemetry.REGISTRY
        self.fleet = fleet
        exporter = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?")[0] != "/metrics":
                    self.send_response(404)
                    self.end_headers()
                    return
                body = exporter.render().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # no stderr spam per scrape
                pass

        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="mxtpu-metrics-exporter", daemon=True)
        self._thread.start()

    def render(self) -> str:
        fleet_summary = None
        if self.fleet is not None:
            try:
                self.fleet.refresh()
                fleet_summary = self.fleet.summary()
            except Exception:
                fleet_summary = None
        return render_prometheus(self.registry.snapshot(),
                                 fleet_summary, registry=self.registry)

    def close(self):
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
        self._thread.join(timeout=5.0)
