"""Distributed request spans: one causal tree per served request.

NEW, fleet-observability plane (ISSUE 14).  A request entering
`serving.FrontDoor.submit` mints a :class:`Trace`; the trace object
rides the existing submit → batcher → engine call chain (and the
shed-retry hop to the next replica), collecting host-side spans —
frontdoor, queue (coalescing wait), prefill, decode — with wall-clock
t0s and microsecond durations.  The closed tree is embedded in the
request's telemetry record (``trace_id`` + ``spans`` fields, schema
v3), so rendering a request's latency waterfall costs ZERO extra
device dispatches and zero extra log records: the span tree travels
inside the record the batcher already emits.

Span semantics (validated by `telemetry._validate_spans`):

- exactly one root span (``parent: null``) per trace — the FrontDoor
  (or the batcher itself for direct submits);
- every span is CLOSED (``dur_us`` >= 0) before the record is
  emitted — open spans are a bug, not a rendering problem;
- ``t0`` is epoch seconds (host wall clock), so spans from different
  replicas/processes order on one timeline (NTP-grade skew applies,
  same caveat as every distributed tracer);
- ``attrs`` carry per-span context (replica id, bucket, generation,
  retry count) — flat JSON scalars only.

Thread-safety: a trace is built by the submitting thread and closed by
the batcher thread; mutation is append/assign under the trace's lock.
"""

from __future__ import annotations

import os
import threading
import time


def new_id() -> str:
    """64-bit random hex id (span and trace ids)."""
    return os.urandom(8).hex()


class Span:
    """One named interval.  ``dur_us`` is None while open."""

    __slots__ = ("span_id", "parent", "name", "t0", "dur_us", "attrs",
                 "_t0_perf")

    def __init__(self, name, parent=None, t0=None):
        self.span_id = new_id()
        self.parent = parent          # parent span_id or None (root)
        self.name = str(name)
        self.t0 = float(t0) if t0 is not None else time.time()
        self.dur_us = None
        self.attrs = {}
        self._t0_perf = time.perf_counter()

    def close(self, dur_us=None, t_end=None):
        """Close the span: explicit duration, explicit end time, or
        elapsed-since-open (monotonic clock)."""
        if dur_us is not None:
            self.dur_us = max(float(dur_us), 0.0)
        elif t_end is not None:
            self.dur_us = max((float(t_end) - self.t0) * 1e6, 0.0)
        else:
            self.dur_us = max(
                (time.perf_counter() - self._t0_perf) * 1e6, 0.0)
        return self

    def to_dict(self) -> dict:
        d = {"span_id": self.span_id, "parent": self.parent,
             "name": self.name, "t0": self.t0,
             "dur_us": round(self.dur_us, 1)
             if self.dur_us is not None else None}
        if self.attrs:
            d["attrs"] = self.attrs
        return d


class Trace:
    """A request's span tree, carried through the serving call chain."""

    def __init__(self, trace_id=None):
        self.trace_id = trace_id or new_id()
        self._spans = []
        self._lock = threading.Lock()

    def begin(self, name, parent=None, t0=None, **attrs) -> Span:
        """Open a span.  `parent` is a Span (or a span_id string);
        None makes it the root."""
        pid = parent.span_id if isinstance(parent, Span) else parent
        sp = Span(name, parent=pid, t0=t0)
        if attrs:
            sp.attrs.update({k: v for k, v in attrs.items()
                             if v is not None})
        with self._lock:
            self._spans.append(sp)
        return sp

    def spans(self):
        with self._lock:
            return list(self._spans)

    def root(self):
        """The root span (parent None), or None before one is begun."""
        with self._lock:
            for sp in self._spans:
                if sp.parent is None:
                    return sp
        return None

    def close_open(self, t_end=None):
        """Close every still-open span (the batcher calls this at
        request completion so upstream spans — the FrontDoor root —
        end with the request)."""
        for sp in self.spans():
            if sp.dur_us is None:
                sp.close(t_end=t_end)
        return self

    def closed(self) -> bool:
        """True when the tree is emittable: non-empty, every span
        closed, exactly one root."""
        spans = self.spans()
        return bool(spans) and \
            all(sp.dur_us is not None for sp in spans) and \
            sum(1 for sp in spans if sp.parent is None) == 1

    def to_fields(self) -> dict:
        """The record fields the batcher passes into
        `telemetry.request_record` — drops any still-open span rather
        than emit an invalid tree."""
        spans = [sp.to_dict() for sp in self.spans()
                 if sp.dur_us is not None]
        return {"trace_id": self.trace_id, "spans": spans}


def render_tree(spans, indent="  ") -> list:
    """ASCII-render a span dict list (as stored in a request record)
    into lines: children nested under parents, durations aligned.
    Used by tools/fleet_report.py; kept here so tests exercise the
    same renderer the CLI ships."""
    by_parent = {}
    by_id = {}
    for sp in spans:
        by_id[sp["span_id"]] = sp
        by_parent.setdefault(sp.get("parent"), []).append(sp)
    for kids in by_parent.values():
        kids.sort(key=lambda s: s.get("t0", 0.0))
    lines = []

    def walk(sp, depth):
        attrs = sp.get("attrs") or {}
        extra = " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))
        dur = sp.get("dur_us")
        dur_txt = f"{dur / 1000.0:8.2f} ms" if dur is not None \
            else "    open  "
        lines.append(f"{indent * depth}{sp['name']:<12} {dur_txt}"
                     f"{('  ' + extra) if extra else ''}")
        for kid in by_parent.get(sp["span_id"], []):
            walk(kid, depth + 1)

    for root in by_parent.get(None, []):
        walk(root, 0)
    # orphans (parent id not in the record) still render, flagged
    known = set(by_id)
    for sp in spans:
        p = sp.get("parent")
        if p is not None and p not in known:
            lines.append(f"?? orphan {sp['name']} (parent {p})")
    return lines
