"""Fleet observability plane (ISSUE 14).

Three stdlib-only pieces on top of PR 7's telemetry and PR 8/11's
gang-KV control plane:

- `obs.spans` — distributed request spans: one causal tree per served
  request, carried inside the request's telemetry record.
- `obs.collector` — per-host JSONL tailing → bounded per-rank rollups
  on the gang KV; `FleetView` aggregates them (fleet MFU, skew,
  straggler attribution, reshape timeline); on-demand `jax.profiler`
  capture via the ``profile/req`` key.
- `obs.exporter` — Prometheus text endpoint over
  `telemetry.MetricsRegistry.snapshot()` + the fleet rollup
  (``MXTPU_METRICS_PORT``).

`ensure_from_env()` is the once-per-process bootstrap: the Trainer
calls it next to `ensure_compile_cache()`; it is a no-op unless
``MXTPU_METRICS_PORT`` or ``MXTPU_OBS_COLLECTOR`` opts in.
"""

from __future__ import annotations

import os
import threading

from .spans import Span, Trace, new_id, render_tree

__all__ = [
    "Span", "Trace", "new_id", "render_tree",
    "HostCollector", "FleetView", "MetricsExporter",
    "ensure_from_env", "shutdown",
]

_LOCK = threading.Lock()
_STARTED = False
_COLLECTOR = None
_EXPORTER = None


def __getattr__(name):
    # lazy submodule attributes: keep `import mxnet_tpu.obs` free of
    # http.server / distributed imports until actually used
    if name in ("HostCollector", "FleetView"):
        from . import collector

        return getattr(collector, name)
    if name in ("MetricsExporter", "render_prometheus"):
        from . import exporter

        return getattr(exporter, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def ensure_from_env():
    """Start the exporter and/or collector once per process when the
    env opts in; safe to call from every Trainer construction.

    - ``MXTPU_METRICS_PORT`` set → MetricsExporter on that port (0 =
      ephemeral), with a FleetView attached when a gang KV exists.
    - ``MXTPU_OBS_COLLECTOR=1`` (or a metrics port + a telemetry path)
      → HostCollector publishing rollups every MXTPU_OBS_ROLLUP_SECS.

    Returns (collector, exporter) — either may be None."""
    global _STARTED, _COLLECTOR, _EXPORTER
    with _LOCK:
        if _STARTED:
            return _COLLECTOR, _EXPORTER
        _STARTED = True
        port_raw = os.environ.get("MXTPU_METRICS_PORT")
        want_collector = os.environ.get(
            "MXTPU_OBS_COLLECTOR", "").lower() in ("1", "true", "on")
        from .. import telemetry

        if port_raw is None and not want_collector:
            return None, None
        kv = None
        try:
            from .. import distributed

            kv = distributed.gang_kv()
        except Exception:
            kv = None
        if (want_collector or port_raw is not None) \
                and telemetry.telemetry_path():
            try:
                from .collector import HostCollector

                _COLLECTOR = HostCollector(kv=kv).start()
            except Exception:
                _COLLECTOR = None
        if port_raw is not None:
            try:
                from .collector import FleetView
                from .exporter import MetricsExporter

                fleet = FleetView(kv) if kv is not None else None
                _EXPORTER = MetricsExporter(port=int(port_raw),
                                            fleet=fleet)
            except Exception:
                _EXPORTER = None
        return _COLLECTOR, _EXPORTER


def shutdown():
    """Stop whatever ensure_from_env started (test isolation)."""
    global _STARTED, _COLLECTOR, _EXPORTER
    with _LOCK:
        if _COLLECTOR is not None:
            _COLLECTOR.close()
        if _EXPORTER is not None:
            _EXPORTER.close()
        _COLLECTOR = _EXPORTER = None
        _STARTED = False
